"""The standard distribution zoo.

Parity: `python/paddle/distribution/` — normal.py, uniform.py,
bernoulli.py, categorical.py, beta.py, dirichlet.py, gamma.py, laplace.py,
exponential.py, lognormal.py, gumbel.py, geometric.py, poisson.py,
multinomial.py.  One module instead of one file per class; each class
documents its reference file.

Sampling: base randomness comes from the framework PRNG (`framework/
random.next_key`), drawn through registered ops so `rsample` is
reparameterized on the eager tape (pathwise gradients for normal/uniform/
gamma-family).  Densities are written with paddle ops, so `log_prob` is
differentiable everywhere.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from ..framework import random as _random
from ..framework.tensor import Tensor
from ..ops.registry import dispatch as _d, register_op
from .distribution import Distribution, _t

__all__ = ["Normal", "Uniform", "Bernoulli", "Categorical", "Beta",
           "Dirichlet", "Gamma", "Laplace", "Exponential", "LogNormal",
           "Gumbel", "Geometric", "Poisson", "Multinomial"]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


# ------------------------------------------------------- sampling primitives
def _reg(name, fn):
    register_op(name, fn)
    return name


_GAMMA = _reg("random_gamma",
              lambda a, key=None, shape=None:
              jax.random.gamma(key, a, shape=shape, dtype=a.dtype))
_POISSON = _reg("random_poisson",
                lambda rate, key=None, shape=None:
                jax.random.poisson(key, rate, shape=shape).astype(jnp.int32))
_CATEG = _reg("random_categorical",
              lambda logits, key=None, shape=None:
              jax.random.categorical(key, logits, shape=shape))


def _gamma_sample(conc: Tensor, shape) -> Tensor:
    return _d(_GAMMA, (conc,), {"key": _random.next_key(), "shape": shape})


# ------------------------------------------------------------ distributions
class Normal(Distribution):
    """Parity: `distribution/normal.py`."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    @property
    def mean(self):
        return self.loc * paddle.ones_like(self.scale)

    @property
    def variance(self):
        return (self.scale * paddle.ones_like(self.loc)) ** 2

    @property
    def stddev(self):
        return self.scale * paddle.ones_like(self.loc)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        eps = paddle.randn(list(out_shape))
        return self.loc + self.scale * eps

    def log_prob(self, value):
        value = _t(value)
        var = self.scale ** 2
        return -((value - self.loc) ** 2) / (2.0 * var) \
            - paddle.log(self.scale) - _HALF_LOG_2PI

    def entropy(self):
        return 0.5 + _HALF_LOG_2PI + paddle.log(
            self.scale * paddle.ones_like(self.loc))

    def cdf(self, value):
        value = _t(value)
        return 0.5 * (1.0 + paddle.erf(
            (value - self.loc) / (self.scale * math.sqrt(2.0))))


class Uniform(Distribution):
    """Parity: `distribution/uniform.py`."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(np.broadcast_shapes(self.low.shape,
                                                   self.high.shape)))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12.0

    def rsample(self, shape=()):
        u = paddle.rand(list(self._extend_shape(shape)))
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        value = _t(value)
        inside = paddle.logical_and(value >= self.low, value < self.high)
        lp = -paddle.log(self.high - self.low)
        return paddle.where(inside, lp * paddle.ones_like(value),
                            paddle.full_like(value, -float("inf")))

    def entropy(self):
        return paddle.log(self.high - self.low)

    def cdf(self, value):
        value = _t(value)
        return paddle.clip((value - self.low) / (self.high - self.low),
                           0.0, 1.0)


class Bernoulli(Distribution):
    """Parity: `distribution/bernoulli.py`."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        with paddle.no_grad():
            u = paddle.rand(list(self._extend_shape(shape)))
            return paddle.cast(u < self.probs, "float32")

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-sigmoid (binary Concrete) relaxation: differentiable
        w.r.t. probs; hardens toward {0,1} as temperature -> 0."""
        p = paddle.clip(self.probs, 1e-7, 1.0 - 1e-7)
        logits = paddle.log(p) - paddle.log1p(-p)
        u = paddle.clip(paddle.rand(list(self._extend_shape(shape))),
                        1e-7, 1.0 - 1e-7)
        logistic = paddle.log(u) - paddle.log1p(-u)
        import paddle_tpu.nn.functional as F
        return F.sigmoid((logits + logistic) / float(temperature))

    def log_prob(self, value):
        value = _t(value)
        p = paddle.clip(self.probs, 1e-7, 1.0 - 1e-7)
        return value * paddle.log(p) + (1.0 - value) * paddle.log(1.0 - p)

    def entropy(self):
        p = paddle.clip(self.probs, 1e-7, 1.0 - 1e-7)
        return -(p * paddle.log(p) + (1 - p) * paddle.log(1 - p))


class Categorical(Distribution):
    """Parity: `distribution/categorical.py` (logits = unnormalized log
    probabilities, reference semantics)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        super().__init__(tuple(self.logits.shape[:-1]))
        self._n = self.logits.shape[-1]

    @property
    def probs(self):
        import paddle_tpu.nn.functional as F
        return F.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        with paddle.no_grad():
            out_shape = tuple(shape) + self._batch_shape
            return _d(_CATEG, (self.logits,),
                      {"key": _random.next_key(),
                       "shape": out_shape if out_shape else None})

    def log_prob(self, value):
        value = _t(value)
        logp = self.logits - paddle.logsumexp(self.logits, axis=-1,
                                              keepdim=True)
        idx = paddle.cast(value, "int64")
        oh = paddle.one_hot(idx, self._n)
        return paddle.sum(oh * logp, axis=-1)

    def probabilities(self, value):
        return paddle.exp(self.log_prob(value))

    def entropy(self):
        logp = self.logits - paddle.logsumexp(self.logits, axis=-1,
                                              keepdim=True)
        return -paddle.sum(paddle.exp(logp) * logp, axis=-1)


class Beta(Distribution):
    """Parity: `distribution/beta.py` (two-gamma sampling)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(np.broadcast_shapes(self.alpha.shape,
                                                   self.beta.shape)))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        a = _gamma_sample(self.alpha * paddle.ones(list(out_shape)), None)
        b = _gamma_sample(self.beta * paddle.ones(list(out_shape)), None)
        return a / (a + b)

    def _log_norm(self):
        return paddle.lgamma(self.alpha) + paddle.lgamma(self.beta) \
            - paddle.lgamma(self.alpha + self.beta)

    def log_prob(self, value):
        value = _t(value)
        return (self.alpha - 1.0) * paddle.log(value) \
            + (self.beta - 1.0) * paddle.log(1.0 - value) - self._log_norm()

    def entropy(self):
        a, b = self.alpha, self.beta
        return self._log_norm() \
            - (a - 1.0) * paddle.digamma(a) - (b - 1.0) * paddle.digamma(b) \
            + (a + b - 2.0) * paddle.digamma(a + b)


class Dirichlet(Distribution):
    """Parity: `distribution/dirichlet.py`."""

    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shape = tuple(self.concentration.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.concentration / paddle.sum(self.concentration, axis=-1,
                                               keepdim=True)

    @property
    def variance(self):
        a0 = paddle.sum(self.concentration, axis=-1, keepdim=True)
        m = self.concentration / a0
        return m * (1.0 - m) / (a0 + 1.0)

    def rsample(self, shape=()):
        out_shape = tuple(shape) + tuple(self.concentration.shape)
        g = _gamma_sample(self.concentration * paddle.ones(list(out_shape)),
                          None)
        return g / paddle.sum(g, axis=-1, keepdim=True)

    def log_prob(self, value):
        value = _t(value)
        a = self.concentration
        log_norm = paddle.sum(paddle.lgamma(a), axis=-1) \
            - paddle.lgamma(paddle.sum(a, axis=-1))
        return paddle.sum((a - 1.0) * paddle.log(value), axis=-1) - log_norm

    def entropy(self):
        a = self.concentration
        a0 = paddle.sum(a, axis=-1)
        k = float(a.shape[-1])
        log_norm = paddle.sum(paddle.lgamma(a), axis=-1) - paddle.lgamma(a0)
        return log_norm + (a0 - k) * paddle.digamma(a0) \
            - paddle.sum((a - 1.0) * paddle.digamma(a), axis=-1)


class Gamma(Distribution):
    """Parity: `distribution/gamma.py` (concentration/rate)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(tuple(np.broadcast_shapes(
            self.concentration.shape, self.rate.shape)))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate ** 2)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(shape)
        g = _gamma_sample(self.concentration * paddle.ones(list(out_shape)),
                          None)
        return g / self.rate

    def log_prob(self, value):
        value = _t(value)
        a, r = self.concentration, self.rate
        return a * paddle.log(r) - paddle.lgamma(a) \
            + (a - 1.0) * paddle.log(value) - r * value

    def entropy(self):
        a, r = self.concentration, self.rate
        return a - paddle.log(r) + paddle.lgamma(a) \
            + (1.0 - a) * paddle.digamma(a)


class Laplace(Distribution):
    """Parity: `distribution/laplace.py`."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    @property
    def mean(self):
        return self.loc * paddle.ones_like(self.scale)

    @property
    def variance(self):
        return 2.0 * (self.scale * paddle.ones_like(self.loc)) ** 2

    def rsample(self, shape=()):
        u = paddle.rand(list(self._extend_shape(shape))) - 0.5
        return self.loc - self.scale * paddle.sign(u) * paddle.log1p(
            -2.0 * paddle.abs(u) + 1e-12)

    def log_prob(self, value):
        value = _t(value)
        return -paddle.abs(value - self.loc) / self.scale \
            - paddle.log(2.0 * self.scale)

    def entropy(self):
        return 1.0 + paddle.log(2.0 * self.scale *
                                paddle.ones_like(self.loc))

    def cdf(self, value):
        value = _t(value)
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * paddle.sign(z) * paddle.expm1(-paddle.abs(z))


class Exponential(Distribution):
    """Parity: `distribution/exponential.py`."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / (self.rate ** 2)

    def rsample(self, shape=()):
        u = paddle.rand(list(self._extend_shape(shape)))
        return -paddle.log1p(-u + 1e-12) / self.rate

    def log_prob(self, value):
        value = _t(value)
        return paddle.log(self.rate) - self.rate * value

    def entropy(self):
        return 1.0 - paddle.log(self.rate)

    def cdf(self, value):
        return -paddle.expm1(-self.rate * _t(value))


class LogNormal(Distribution):
    """Parity: `distribution/lognormal.py`."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return paddle.exp(self.loc + (self.scale ** 2) / 2.0)

    @property
    def variance(self):
        s2 = self.scale ** 2
        return paddle.expm1(s2) * paddle.exp(2.0 * self.loc + s2)

    def rsample(self, shape=()):
        return paddle.exp(self._base.rsample(shape))

    def log_prob(self, value):
        value = _t(value)
        return self._base.log_prob(paddle.log(value)) - paddle.log(value)

    def entropy(self):
        return self._base.entropy() + self.loc


class Gumbel(Distribution):
    """Parity: `distribution/gumbel.py`."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    @property
    def mean(self):
        return self.loc + self.scale * 0.57721566490153286  # Euler gamma

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale ** 2

    def rsample(self, shape=()):
        u = paddle.rand(list(self._extend_shape(shape)))
        return self.loc - self.scale * paddle.log(
            -paddle.log(u + 1e-12) + 1e-12)

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return -(z + paddle.exp(-z)) - paddle.log(self.scale)

    def entropy(self):
        return paddle.log(self.scale * paddle.ones_like(self.loc)) \
            + 1.0 + 0.57721566490153286

    def cdf(self, value):
        z = (_t(value) - self.loc) / self.scale
        return paddle.exp(-paddle.exp(-z))


class Geometric(Distribution):
    """Parity: `distribution/geometric.py` (trials before first success,
    support {0, 1, 2, ...})."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return (1.0 - self.probs) / self.probs

    @property
    def variance(self):
        return (1.0 - self.probs) / (self.probs ** 2)

    def sample(self, shape=()):
        with paddle.no_grad():
            u = paddle.rand(list(self._extend_shape(shape)))
            return paddle.floor(paddle.log(u + 1e-12) /
                                paddle.log1p(-self.probs + 1e-12))

    def log_prob(self, value):
        value = _t(value)
        return value * paddle.log1p(-self.probs + 1e-12) \
            + paddle.log(self.probs)

    def entropy(self):
        p = self.probs
        q = 1.0 - p
        return -(q * paddle.log(q + 1e-12) + p * paddle.log(p)) / p


class Poisson(Distribution):
    """Parity: `distribution/poisson.py`."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        with paddle.no_grad():
            out_shape = self._extend_shape(shape)
            return paddle.cast(
                _d(_POISSON, (self.rate,),
                   {"key": _random.next_key(),
                    "shape": out_shape if out_shape else None}), "float32")

    def log_prob(self, value):
        value = _t(value)
        return value * paddle.log(self.rate) - self.rate \
            - paddle.lgamma(value + 1.0)

    def entropy(self):
        # second-order Stirling approximation (reference uses the same
        # truncated series)
        r = self.rate
        return 0.5 * paddle.log(2.0 * math.pi * math.e * r) \
            - 1.0 / (12.0 * r) - 1.0 / (24.0 * r * r)


class Multinomial(Distribution):
    """Parity: `distribution/multinomial.py`."""

    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shape = tuple(self.probs.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.probs * float(self.total_count)

    @property
    def variance(self):
        return float(self.total_count) * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        with paddle.no_grad():
            logits = paddle.log(self.probs + 1e-12)
            draw_shape = (self.total_count,) + tuple(shape) \
                + self._batch_shape
            draws = _d(_CATEG, (logits,),
                       {"key": _random.next_key(), "shape": draw_shape})
            k = self.probs.shape[-1]
            counts = paddle.sum(paddle.one_hot(draws, k), axis=0)
            return counts

    def log_prob(self, value):
        value = _t(value)
        return paddle.lgamma(_t(float(self.total_count)) + 1.0) \
            - paddle.sum(paddle.lgamma(value + 1.0), axis=-1) \
            + paddle.sum(value * paddle.log(self.probs + 1e-12), axis=-1)
