"""Trainer-side auto-resume glue: the elastic training loop (ISSUE 20).

`run_elastic` composes the two halves that PR 19 left manual: the
launcher's restart generations (`distributed/launch/main.py` — a node
death or worker crash bumps `restart_generation` and the world
re-settles) and the ZeRO-3 reshard-on-resume
(`fleet.hybrid_step.load_zero3_state` →
`restore_into(resize_trailing=True)`).  Every worker process runs the
same loop: read the settled world from the launcher-provided env,
restore the latest COMPLETE checkpoint if one exists (whatever dp degree
wrote it), then step — so after ANY generation bump the re-spawned
workers resume where the fleet left off with zero operator action.

`ProgressReporter` is the worker half of the launcher's progress
watchdog (`FLAGS_elastic_stall_timeout_s`): it publishes a monotonic
step heartbeat to `progress/{generation}/{rank}` on the rendezvous
store.  Publishing is strictly optional — a script that never reports is
never stall-killed — and strictly best-effort: a store hiccup drops a
heartbeat, it never breaks training.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from ....testing import chaos as _chaos
from ...launch.main import _event, _metric
from ...store import TCPStore

__all__ = ["ElasticContext", "ProgressReporter", "run_elastic",
           "zero3_elastic_hooks"]


@dataclass
class ElasticContext:
    """The settled world as the launcher told this worker about it."""

    generation: int
    rank: int
    world_size: int
    local_rank: int
    nnodes: int
    master: Optional[str]

    @classmethod
    def from_env(cls, env=None) -> "ElasticContext":
        e = os.environ if env is None else env
        return cls(
            generation=int(e.get("PADDLE_RESTART_GENERATION", "0")),
            rank=int(e.get("PADDLE_TRAINER_ID", "0")),
            world_size=int(e.get("PADDLE_TRAINERS_NUM", "1")),
            local_rank=int(e.get("PADDLE_LOCAL_RANK", "0")),
            nnodes=int(e.get("PADDLE_NNODES", "1")),
            master=e.get("PADDLE_MASTER") or None,
        )


class ProgressReporter:
    """Publish the worker's step heartbeat for the stall watchdog.

    Chaos: every publish passes the ``elastic.step`` delay site, so
    :func:`paddle_tpu.testing.chaos.delay_at` can freeze a worker's
    heartbeat in place — the deterministic stand-in for a wedged
    collective the watchdog must kill."""

    def __init__(self, ctx: Optional[ElasticContext] = None,
                 store: Optional[TCPStore] = None, env=None):
        self.ctx = ctx or ElasticContext.from_env(env)
        self._store = store
        self._enabled = store is not None or bool(self.ctx.master)

    def _get_store(self) -> Optional[TCPStore]:
        if self._store is None and self._enabled:
            host, port = self.ctx.master.rsplit(":", 1)
            try:
                self._store = TCPStore(host=host, port=int(port))
            except (OSError, TimeoutError, ValueError):
                self._enabled = False  # no store, no heartbeats — fine
        return self._store

    def publish(self, step: int) -> None:
        _chaos.maybe_delay("elastic.step")
        if not self._enabled:
            return
        store = self._get_store()
        if store is None:
            return
        key = f"progress/{self.ctx.generation}/{self.ctx.rank}"
        try:
            store.set(key, str(int(step)))
        except (OSError, TimeoutError):
            pass  # best-effort: a dropped heartbeat never kills training


def run_elastic(step_fn: Callable[[Any, int, ElasticContext], Any],
                manager,
                *,
                init_fn: Callable[[ElasticContext], Tuple[Any, int]],
                restore_fn: Callable[[Any, ElasticContext],
                                     Tuple[Any, int]],
                save_fn: Optional[Callable[..., Any]] = None,
                max_steps: int,
                save_every: int = 1,
                ctx: Optional[ElasticContext] = None,
                reporter: Optional[ProgressReporter] = None,
                env=None) -> Tuple[Any, int]:
    """Run `step_fn` to `max_steps` under elastic supervision.

    On entry (every generation — the launcher re-execs workers after a
    bump) the loop asks `manager.latest_complete()`: a COMPLETE
    checkpoint means this is a resume and `restore_fn(manager, ctx)`
    rebuilds `(state, start_step)` against the CURRENT settled world
    (for ZeRO-3, :func:`zero3_elastic_hooks` routes this through
    `load_zero3_state`'s trailing-dim reshard); no checkpoint means a
    cold `init_fn(ctx)`.  Each completed step publishes the watchdog
    heartbeat; every `save_every` steps `save_fn(manager, step, state,
    ctx)` versions the state so the NEXT death costs at most
    `save_every` steps of recompute.

    What resume restores: exactly what `save_fn` saved — model/optimizer
    state and the step counter.  What it does NOT: dataloader position,
    RNG streams or host-side Python state; deterministic re-derivation
    from the step index (as the drill scripts do) is the caller's job.

    Returns ``(state, steps_completed)``."""
    ctx = ctx or ElasticContext.from_env(env)
    rep = reporter or ProgressReporter(ctx=ctx, env=env)
    _metric("gauge", "elastic.generation", ctx.generation,
            "current elastic restart generation of this launcher")
    latest = manager.latest_complete()
    if latest is not None:
        state, step = restore_fn(manager, ctx)
        _metric("counter", "elastic.resumes_total", 1,
                "elastic auto-resumes from a COMPLETE checkpoint "
                "(one per worker per restart generation)")
        _event("elastic_resume", generation=ctx.generation, step=step,
               world_size=ctx.world_size, checkpoint=latest)
    else:
        state, step = init_fn(ctx)
    while step < max_steps:
        state = step_fn(state, step, ctx)
        step += 1
        rep.publish(step)
        if save_fn is not None and save_every > 0 \
                and step % save_every == 0:
            save_fn(manager, step, state, ctx)
    return state, step


def zero3_elastic_hooks(mesh, cfg, params_fn, grain: int = 0):
    """Hook triple wiring :func:`run_elastic` to the PR 19 fused ZeRO-3
    state: cold start flattens `params_fn(ctx)` into (Fp,) dp shards,
    resume reloads through `load_zero3_state` (bit-exact at any dp
    degree when the run uses a fixed reduction `grain`), saves version
    the flat shards + Adam moments through `save_zero3_state`.

    Returns ``(init_fn, restore_fn, save_fn)``."""
    from .. import hybrid_step as hs

    def init_fn(ctx):
        flat, m, v = hs.init_zero3_state(params_fn(ctx), mesh)
        return {"flat": flat, "m": m, "v": v,
                "step_no": 0.0, "grain": int(grain)}, 0

    def restore_fn(manager, ctx):
        flat, m, v, step_no, g = hs.load_zero3_state(manager, mesh, cfg)
        state = {"flat": flat, "m": m, "v": v,
                 "step_no": step_no, "grain": int(g)}
        return state, int(manager.latest_complete())

    def save_fn(manager, step, state, ctx):
        hs.save_zero3_state(manager, step, state["flat"], state["m"],
                            state["v"], state["step_no"], state["grain"],
                            wait=True)

    return init_fn, restore_fn, save_fn
