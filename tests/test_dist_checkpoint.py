"""Distributed checkpoint: save/load round trips with reshard-on-load.

Mirrors the reference's `test/auto_parallel/test_dist_checkpoint_utils.py` /
`semi_auto_parallel_checkpoint_*` strategy: save under one mesh/sharding,
load under another, values and training trajectories must be identical.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def mesh_1d(n, name="x"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def mesh_2d(a, b, names=("dp", "mp")):
    return Mesh(np.array(jax.devices()[:a * b]).reshape(a, b), names)


def shard_value(arr, mesh, spec):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


def test_replicated_round_trip(tmp_path):
    w = np.arange(32, dtype=np.float32).reshape(4, 8)
    sd = {"w": paddle.to_tensor(w), "b": paddle.to_tensor(np.ones(3, np.float32))}
    dist.save_state_dict(sd, str(tmp_path))
    target = {"w": paddle.zeros([4, 8]), "b": paddle.zeros([3])}
    dist.load_state_dict(target, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(target["w"]._value), w)
    np.testing.assert_array_equal(np.asarray(target["b"]._value), np.ones(3))


def test_nested_flatten_round_trip(tmp_path):
    sd = {"model": {"fc.w": paddle.to_tensor(np.ones((2, 2), np.float32))},
          "opt": {"moment1": {"fc.w": paddle.to_tensor(
              np.full((2, 2), 3.0, np.float32))}}}
    dist.save_state_dict(sd, str(tmp_path))
    target = {"model": {"fc.w": paddle.zeros([2, 2])},
              "opt": {"moment1": {"fc.w": paddle.zeros([2, 2])}}}
    dist.load_state_dict(target, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(target["opt"]["moment1"]["fc.w"]._value), 3.0)


def test_sharded_save_resharded_load(tmp_path):
    """Save Shard(0) over 4 devices, load Shard(1) over 2 and replicated."""
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    m4 = mesh_1d(4)
    t = paddle.Tensor._wrap(shard_value(w, m4, P("x", None)))
    dist.save_state_dict({"w": t}, str(tmp_path))

    # load into a different axis sharding on a smaller mesh
    m2 = mesh_1d(2, "y")
    tgt = paddle.Tensor._wrap(shard_value(np.zeros_like(w), m2, P(None, "y")))
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt._value), w)
    assert tgt._value.sharding.spec == P(None, "y")

    # and into a replicated target
    tgt2 = paddle.to_tensor(np.zeros_like(w))
    dist.load_state_dict({"w": tgt2}, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt2._value), w)


def test_2d_sharded_to_2d_sharded(tmp_path):
    """dp2xmp2 2-D sharding -> mp4 sharding on the other dim."""
    w = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    t = paddle.Tensor._wrap(shard_value(w, mesh_2d(2, 2), P("dp", "mp")))
    dist.save_state_dict({"w": t}, str(tmp_path))

    m4 = Mesh(np.array(jax.devices()[:4]), ("mp",))
    tgt = paddle.Tensor._wrap(shard_value(np.zeros_like(w), m4, P("mp", None)))
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt._value), w)


def test_missing_key_raises(tmp_path):
    dist.save_state_dict({"a": paddle.ones([2])}, str(tmp_path))
    with pytest.raises(KeyError):
        dist.load_state_dict({"nope": paddle.zeros([2])}, str(tmp_path))


def test_shape_mismatch_raises(tmp_path):
    dist.save_state_dict({"a": paddle.ones([2, 3])}, str(tmp_path))
    with pytest.raises(ValueError):
        dist.load_state_dict({"a": paddle.zeros([3, 2])}, str(tmp_path))


def test_async_save(tmp_path):
    sd = {"w": paddle.to_tensor(np.full((128, 128), 7.0, np.float32))}
    dist.save_state_dict(sd, str(tmp_path), async_save=True)
    dist.checkpoint.wait_async_save()
    tgt = {"w": paddle.zeros([128, 128])}
    dist.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt["w"]._value), 7.0)


def test_crash_between_data_and_metadata_leaves_no_stale_merge(tmp_path):
    """Regression (ISSUE 5 satellite): a save that dies after rewriting
    the data file but BEFORE the metadata write used to leave the
    previous save's same-rank .metadata pointing into the new data file —
    load would silently merge them.  Both files are deleted up front now,
    so the half-written save is simply invisible."""
    from paddle_tpu.testing import chaos
    dist.save_state_dict({"a": paddle.to_tensor(np.ones(4, np.float32))},
                         str(tmp_path))
    assert (tmp_path / "0.metadata").exists()
    with chaos.fail_open(".metadata", on_calls=[1]):
        with pytest.raises(OSError):
            dist.save_state_dict(
                {"a": paddle.to_tensor(np.full(4, 2.0, np.float32))},
                str(tmp_path))
    # the stale metadata is gone with the crashed save…
    assert not (tmp_path / "0.metadata").exists()
    # …so load refuses with a clear error instead of merging old+new
    with pytest.raises(ValueError, match="no .metadata"):
        dist.load_state_dict({"a": paddle.zeros([4])}, str(tmp_path))


def test_load_missing_directory_clear_error(tmp_path):
    missing = str(tmp_path / "nope")
    with pytest.raises(FileNotFoundError, match="nope"):
        dist.load_state_dict({"a": paddle.zeros([2])}, missing)


def test_load_empty_directory_clear_error(tmp_path):
    with pytest.raises(ValueError, match="no .metadata"):
        dist.load_state_dict({"a": paddle.zeros([2])}, str(tmp_path))


def test_read_state_dict_full_assembly(tmp_path):
    """read_state_dict reassembles a sharded checkpoint template-free."""
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    t = paddle.Tensor._wrap(shard_value(w, mesh_1d(4), P("x", None)))
    dist.save_state_dict({"nest": {"w": t}, "b": paddle.ones([3])},
                         str(tmp_path))
    out = dist.checkpoint.read_state_dict(str(tmp_path))
    np.testing.assert_array_equal(out["nest"]["w"], w)
    np.testing.assert_array_equal(out["b"], np.ones(3, np.float32))


def test_training_resumes_identically_across_reshard(tmp_path):
    """Train 2 steps sharded dp2xmp2, checkpoint, resume under mp4: the
    continued trajectory must match an uninterrupted serial run."""
    rng = np.random.RandomState(0)
    w0 = rng.randn(8, 8).astype(np.float32)
    xs = rng.randn(4, 8, 8).astype(np.float32)

    def step(w, x):
        loss = jnp.mean((x @ w) ** 2)
        g = jax.grad(lambda w: jnp.mean((x @ w) ** 2))(w)
        return loss, w - 0.1 * g

    # uninterrupted serial reference
    w = jnp.asarray(w0)
    ref_losses = []
    for i in range(4):
        l, w = step(w, jnp.asarray(xs[i]))
        ref_losses.append(float(l))

    # phase 1: dp2 x mp2 sharded weight
    wA = shard_value(w0, mesh_2d(2, 2), P("dp", "mp"))
    got = []
    for i in range(2):
        l, wA = step(wA, jnp.asarray(xs[i]))
        got.append(float(l))
    dist.save_state_dict({"w": paddle.Tensor._wrap(wA)}, str(tmp_path))

    # phase 2: resume under a 4-way model-parallel sharding
    m4 = Mesh(np.array(jax.devices()[:4]), ("mp",))
    tgt = paddle.Tensor._wrap(shard_value(np.zeros_like(w0), m4, P(None, "mp")))
    dist.load_state_dict({"w": tgt}, str(tmp_path))
    wB = tgt._value
    for i in range(2, 4):
        l, wB = step(wB, jnp.asarray(xs[i]))
        got.append(float(l))

    np.testing.assert_allclose(got, ref_losses, rtol=1e-5)
