"""Fused whole-pytree optimizer update: the training-step fast path.

The per-leaf path in `optimizer.py` dispatches one jitted XLA program per
parameter per step (plus two more per parameter for global-norm clipping
and another per parameter for AMP unscaling) — for a GPT-sized pytree
that is hundreds of tiny executables and, with a GradScaler, a forced
device→host `bool(found_inf)` round trip every step.  The reference
solves this with multi-tensor CUDA kernels (`fused_adam_kernel.h`,
`multi_tensor_adam`); the TPU-native analogue is ONE donated jitted
program over the entire flattened pytree that performs, inside a single
executable:

1. AMP unscale (``grad * 1/scale`` per leaf, dtype-preserving),
2. the on-device ``found_inf`` reduction (one OR over per-leaf
   ``any(~isfinite)`` flags, never synced to the host here),
3. gradient clipping — ClipGradByGlobalNorm's fused squared-norm
   reduction + scale (composing with the fleet cross-mesh
   ``_global_norm_reduce_fn`` hook, traced into the program),
   ClipGradByNorm / ClipGradByValue elementwise,
4. the optimizer update for every parameter, including master-weight
   promotion, with ``lax.cond(found_inf)`` skipping the whole update
   (params/masters/states pass through untouched) on an overflow step,
5. the GradScaler's dynamic scale/good/bad bookkeeping, kept as device
   scalars so `GradScaler.step` never blocks the dispatch queue — the
   flag is read back only at the flag-spaced loss sync
   (`GradScaler._sync_fused_state`).

Programs are cached per ``(tree structure + dtypes, per-leaf static
config, clip config, scaler config, donation)`` on the OPTIMIZER
INSTANCE (update rules are per-instance closures over hyperparameters).
Param/master/state buffers are donated so XLA updates them in place in
HBM, exactly like the per-leaf path — and like it, donation is disabled
while the `to_static` state-discovery pass holds rollback references.

Numerics: the fused program replays the per-leaf computation with the
same primitives in the same order (left-fold squared-norm accumulation,
f32 scalar lr/step inputs), so fp32 results are BIT-IDENTICAL to the
per-leaf path (pinned by tests/test_optimizer.py's parity suite).

Fallbacks (counted on the ``optimizer.fused`` counter, kind=fallback):
L1 decay, custom ClipGradBase subclasses, optimizers without a
registered elementwise rule (LBFGS), a global-norm reduce hook that
cannot trace (host-side cross-mesh reductions), ZeRO trees whose leaves
sit on incompatible device placements, and — scaler path only — aux
hooks (the legacy path gates them on the update actually applying).
Per-leaf ``need_clip`` / ``optimize_attr`` learning rates and
group-level overrides are regular enough to stay fused (static masks /
a traced per-leaf LR vector).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from ..framework.tensor import Tensor
from ..observability import compile_tracker as _compile_tracker
from ..observability import metrics as _metrics

__all__ = ["enabled", "try_step", "scaler_step", "zero3_shard_update"]

# hit = cached program reused; miss = new (tree, config) program traced;
# fallback = irregular step served by the per-leaf path
_M_FUSED = _metrics.counter(
    "optimizer.fused",
    "fused train-update outcomes per step (kind=hit|miss|fallback)")
# the optimizer layer's program dispatches ride the same instrument the
# eager op dispatcher uses, so one metrics delta covers a whole step
_M_DISPATCH = _metrics.counter(
    "dispatch.ops", "eager dispatches per op name")
_K_HIT = (("kind", "hit"),)
_K_MISS = (("kind", "miss"),)
_K_FALLBACK = (("kind", "fallback"),)
_K_FUSED_STEP = (("op", "optimizer.fused_step"),)


def enabled() -> bool:
    try:
        return bool(_flags.get_flag("fused_optimizer"))
    except ValueError:  # pragma: no cover - flag always registered
        return False


def zero3_shard_update(p_shards, g_shards, m_shards, v_shards, step, *,
                       learning_rate, beta1, beta2, eps):
    """Fused Adam over 1/N-resident ZeRO-3 shard lists.

    The one-dispatch fused update applied to SHARDED residents: every
    leaf here is one dp rank's flat parameter/moment shard, and the
    whole list updates inside the caller's program (the fused ZeRO-3
    step traces this after its in-program reduce-scatter, so with
    donation the flat shard buffers update in place in HBM — no
    per-leaf dispatch, no full-parameter moment state anywhere).
    Elementwise only, so the math is length-invariant: the same global
    element sees bit-identical updates at any sharding world size,
    which is what the elastic reshard-on-resume drill pins.  Primitive
    order matches `hybrid_step._adam_math` (bit parity with the ZeRO-1/2
    hybrid path's per-shard update)."""
    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(p_shards, g_shards, m_shards, v_shards):
        m2 = beta1 * m + (1 - beta1) * g
        v2 = beta2 * v + (1 - beta2) * jnp.square(g)
        mh = m2 / (1 - beta1 ** step)
        vh = v2 / (1 - beta2 ** step)
        new_p.append(p - learning_rate * mh / (jnp.sqrt(vh) + eps))
        new_m.append(m2)
        new_v.append(v2)
    return new_p, new_m, new_v


def _rule_of(opt):
    """The per-leaf update rule `(w, g, states, lr, wd, step) ->
    (new_w, new_states)` — per-instance closure (Adam family, Momentum)
    or class staticmethod (SGD); None for optimizers without one."""
    r = getattr(opt, "_rule", None)
    if callable(r):
        return r
    r = getattr(opt, "_update_rule", None)
    if isinstance(r, staticmethod):  # Momentum stores an instance staticmethod
        return r.__func__
    return r if callable(r) else None


def _effective_wd(opt, p, wd):
    """Replicates the per-leaf `_apply_one` overrides: AdamW's
    apply_decay_param_fun and Lamb's exclude_from_weight_decay_fn."""
    fn = getattr(opt, "_apply_decay_param_fun", None)
    if fn is not None and not fn(p.name):
        return 0.0
    ex = getattr(opt, "_exclude_fn", None)
    if ex is not None and ex(p):
        return 0.0
    return wd


def _clip_config(opt) -> Tuple[Optional[tuple], Optional[Any], bool]:
    """(static clip key, traced reduce hook, fusible).  Exact-type checks:
    user subclasses of the clip classes fall back to the per-leaf path."""
    clip = opt._grad_clip
    if clip is None:
        return None, None, True
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)
    t = type(clip)
    if t is ClipGradByGlobalNorm:
        hook = clip._global_norm_reduce_fn
        # the hook OBJECT rides in the cache key (functions hash by
        # identity): keeps a strong ref, so a recycled id() can never
        # alias a new hook onto a program traced with the old one
        return (("global", clip.clip_norm, hook), hook, True)
    if t is ClipGradByNorm:
        return ("norm", clip.clip_norm), None, True
    if t is ClipGradByValue:
        return ("value", clip.min, clip.max), None, True
    return None, None, False


def _scaler_config(scaler) -> Optional[tuple]:
    if scaler is None:
        return None
    return ("scaler", float(scaler._incr_ratio), float(scaler._decr_ratio),
            int(scaler._incr_every), int(scaler._decr_every),
            bool(scaler._dynamic))


# the (shape, dtype) cache-key atom every fast-path program cache shares
from ..nn.clip import _aval_key  # noqa: E402


# cache sentinel: this (tree, config) cannot run as one program (e.g.
# leaves committed to incompatible device placements under ZeRO, or a
# host-side _global_norm_reduce_fn hook that cannot trace) — remembered
# so the step doesn't re-raise every iteration
_UNFUSIBLE = object()

# errors that mean "this plan cannot run fused" but are raised BEFORE
# execution (buffers intact, safe to fall back): jit argument/placement
# validation (ValueError) and trace-time concretization of a host-side
# hook (the same family ops/registry treats as trace failures)
_PLAN_ERRORS = (ValueError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.UnexpectedTracerError,
                jax.errors.NonConcreteBooleanIndexError)


def _build_program(rule, statics, clip_cfg, reduce_fn, scaler_cfg, donate):
    """Trace-time factory.  `statics` is a tuple of per-leaf
    (use_master, wd, need_clip); everything per-leaf that the rules
    branch on in Python (wd truthiness) is baked in here."""

    def update_tree(params, grads, masters, states, lrs, step):
        if clip_cfg is not None:
            kind = clip_cfg[0]
            if kind == "global":
                # left-fold accumulation in leaf order — the exact shape
                # of ClipGradByGlobalNorm's eager loop, for bit parity
                sq = None
                for (_, _, nc), g in zip(statics, grads):
                    if not nc:
                        continue
                    s = jnp.sum(jnp.square(g.astype(jnp.float32)))
                    sq = s if sq is None else sq + s
                if sq is not None:
                    if reduce_fn is not None:
                        sq = reduce_fn(sq)
                    gnorm = jnp.sqrt(sq)
                    cscale = clip_cfg[1] / jnp.maximum(gnorm, clip_cfg[1])
                    grads = [(g.astype(jnp.float32) * cscale).astype(g.dtype)
                             if nc else g
                             for (_, _, nc), g in zip(statics, grads)]
            elif kind == "norm":
                cn = clip_cfg[1]

                def clip_one(g):
                    norm = jnp.sqrt(jnp.sum(jnp.square(g)))
                    s = jnp.where(norm > cn, cn / jnp.maximum(norm, 1e-12),
                                  1.0)
                    return g * s
                grads = [clip_one(g) if nc else g
                         for (_, _, nc), g in zip(statics, grads)]
            else:  # value
                lo, hi = clip_cfg[1], clip_cfg[2]
                grads = [jnp.clip(g, lo, hi) if nc else g
                         for (_, _, nc), g in zip(statics, grads)]
            # the per-leaf path rounds clipped grads at its program
            # boundary; fence them here so XLA cannot fma-fuse the clip
            # multiply into the update (bit parity with per-leaf)
            grads = list(jax.lax.optimization_barrier(tuple(grads)))
        new_p, new_m, new_s = [], [], []
        for i, ((use_master, wd, _), p, g, m, st) in enumerate(
                zip(statics, params, grads, masters, states)):
            work = m if use_master else p
            g = g.astype(work.dtype)
            new_w, new_st = rule(work, g, st, lrs[i], wd, step)
            if use_master:
                new_p.append(new_w.astype(p.dtype))
                new_m.append(new_w)
            else:
                new_p.append(new_w)
                new_m.append(None)
            new_s.append(list(new_st))
        return new_p, new_m, new_s

    if scaler_cfg is None:
        def program(params, grads, masters, states, lrs, step):
            return update_tree(params, grads, masters, states, lrs, step)
    else:
        _, incr_ratio, decr_ratio, incr_every, decr_every, dynamic = \
            scaler_cfg

        def program(params, grads, masters, states, lrs, gstep,
                    scale, good, bad, nskip):
            inv = 1.0 / scale
            grads = [g * inv.astype(g.dtype) for g in grads]
            found = jnp.zeros((), jnp.bool_)
            for g in grads:
                found = found | jnp.any(~jnp.isfinite(g))
            # per-leaf rounds unscaled grads at the unscale-program
            # boundary (found is computed inside it — before the fence)
            grads = list(jax.lax.optimization_barrier(tuple(grads)))
            # the legacy path only advances _global_step when the update
            # APPLIES (a skipped step must not advance Adam's bias
            # correction) — so the applied-step count is found-dependent
            # and stays on device with everything else
            new_p, new_m, new_s = jax.lax.cond(
                found,
                lambda: (list(params), list(masters),
                         [list(st) for st in states]),
                lambda: update_tree(params, grads, masters, states, lrs,
                                    (gstep + 1).astype(jnp.float32)))
            new_gstep = jnp.where(found, gstep, gstep + 1)
            # GradScaler.update() replayed on device
            if dynamic:
                bad1 = bad + 1
                good1 = good + 1
                dec = bad1 >= decr_every
                inc = good1 >= incr_every
                scale2 = jnp.where(
                    found,
                    jnp.where(dec, jnp.maximum(scale * decr_ratio, 1.0),
                              scale),
                    jnp.where(inc, scale * incr_ratio, scale))
                good2 = jnp.where(found, 0, jnp.where(inc, 0, good1))
                bad2 = jnp.where(found, jnp.where(dec, 0, bad1), 0)
            else:
                scale2, good2, bad2 = scale, good, bad
            nskip2 = nskip + found.astype(nskip.dtype)
            # the legacy path writes UNSCALED (not clipped) grads back to
            # p.grad; return them so post-step grad introspection matches
            return (new_p, new_m, new_s, grads, new_gstep,
                    (found, scale2, good2, bad2, nskip2))

    return jax.jit(program,
                   donate_argnums=(0, 2, 3) if donate else ())


def _plan(opt, work, scaler, clip_static):
    """Resolve (or build) the fused program for this step's pytree.
    `clip_static` is (clip_key, reduce_fn) to embed in the program, or
    (None, None) when clipping is handled outside (or absent).  Returns
    None when the step is irregular — caller falls back."""
    rule = _rule_of(opt)
    if rule is None:
        return None
    clip_key, reduce_fn = clip_static
    scaler_cfg = _scaler_config(scaler)
    from .optimizer import _donation_safe
    # CPU PJRT doesn't implement donation (same gate as to_static's
    # whole-step programs, jit/api.py) — observed to corrupt the heap
    # under the persistent compile cache; donation is a TPU/HBM feature
    donate = _donation_safe() and jax.default_backend() != "cpu"
    state_names = list(opt._state_names)

    leaves = []   # (p, grad_value, lr, use_master, wd, need_clip)
    for p, g, lr, wd, l1 in work:
        if l1:
            return None  # L1Decay's sign-term stays on the per-leaf path
        gv = g._value if isinstance(g, Tensor) else g
        use_master = opt._multi_precision and p._value.dtype in (
            jnp.float16, jnp.bfloat16)
        wd_eff = _effective_wd(opt, p, wd)
        lr_eff = lr * p.optimize_attr.get("learning_rate", 1.0)
        need_clip = bool(getattr(p, "need_clip", True))
        leaves.append((p, gv, lr_eff, use_master, wd_eff, need_clip))

    statics = tuple((um, wd, nc) for _, _, _, um, wd, nc in leaves)
    # gather state/master arrays now: their actual dtypes (possibly loaded
    # from a checkpoint) are part of the program signature
    masters = [opt._create_master_weight(p) if um else None
               for p, _, _, um, _, _ in leaves]
    states = [[opt._get_state(n, p) for n in state_names]
              for p, _, _, _, _, _ in leaves]
    key = (statics, clip_key, scaler_cfg, donate, tuple(state_names),
           tuple(_aval_key(p._value) for p, *_ in leaves),
           tuple(_aval_key(gv) for _, gv, *_ in leaves),
           tuple(_aval_key(m) if m is not None else None for m in masters),
           tuple(tuple(_aval_key(s) for s in st) for st in states))
    try:
        hash(key)
    except TypeError:
        return None
    cache: Dict[Any, Any] = opt.__dict__.setdefault("_fused_programs", {})
    prog = cache.get(key)
    if prog is _UNFUSIBLE:
        return None
    if prog is None:
        _M_FUSED.inc_key(_K_MISS)
        # recompile blame (ISSUE 6): the first call of a fresh fused
        # program is where the trace+XLA compile lands; the signature
        # names what re-triggers it (a new leaf aval, clip/scaler config)
        blame_sig = (("leaves", len(leaves)),
                     ("clip", repr(clip_key)[:120]),
                     ("scaler", scaler_cfg is not None),
                     ("donate", donate),
                     ("params", tuple(repr(_aval_key(p._value))
                                      for p, *_ in leaves)))
        prog = cache[key] = _compile_tracker.wrap_first_call(
            _build_program(rule, statics, clip_key, reduce_fn,
                           scaler_cfg, donate),
            "optimizer.fused_step", blame_sig)
    elif _metrics._ENABLED:
        _M_FUSED.inc_key(_K_HIT)
    return prog, key, leaves, masters, states, state_names


def _execute(opt, plan, scaler, grads_override=None):
    prog, _key, leaves, masters, states, state_names = plan
    params = [p._value for p, *_ in leaves]
    grads = grads_override if grads_override is not None \
        else [gv for _, gv, *_ in leaves]
    lr_list = [lr for _, _, lr, _, _, _ in leaves]
    if all(isinstance(lr, float) for lr in lr_list):
        # one H2D put (np rounds f64->f32 exactly like per-leaf asarray)
        lrs = jnp.asarray(np.asarray(lr_list, np.float32))
    else:  # traced LR (to_static capture): stack the tracers
        lrs = jnp.stack([jnp.asarray(lr, jnp.float32) for lr in lr_list])
    if _metrics._ENABLED:
        _M_DISPATCH.inc_key(_K_FUSED_STEP)
    if scaler is None:
        step = jnp.asarray(opt._global_step, jnp.float32)
        new_p, new_m, new_s = prog(params, grads, masters, states, lrs, step)
    else:
        # the caller did NOT pre-increment _global_step: whether this
        # step applies is found_inf-dependent, so the program returns the
        # new applied-step count as a device scalar
        gstep = jnp.asarray(opt._global_step, jnp.int32)
        scale, good, bad, nskip = scaler._fused_state()
        new_p, new_m, new_s, out_grads, new_gstep, sc_out = prog(
            params, grads, masters, states, lrs, gstep,
            scale, good, bad, nskip)
        opt._global_step = new_gstep
        scaler._fused_commit(*sc_out)
        for (p, *_), g in zip(leaves, out_grads):
            if p.grad is not None:  # legacy parity: grads end up unscaled
                p.grad._value = g
    for i, (p, _, _, use_master, _, _) in enumerate(leaves):
        p._value = new_p[i]
        if use_master:
            opt._accumulators["master_weight"][id(p)] = new_m[i]
        for n, s in zip(state_names, new_s[i]):
            opt._accumulators[n][id(p)] = s
    _poison_donated_inputs(params, masters, states, new_p, new_m, new_s)


def _poison_donated_inputs(params, masters, states, new_p, new_m, new_s):
    """jaxsan (FLAGS_enable_jaxsan, default off): the fused program
    donates params/masters/states on TPU — on CPU donation is ignored,
    so a stale reference to a pre-step buffer reads plausible bytes in
    every CPU test and garbage in production.  Poisoning the superseded
    input leaves right after the rebind turns that latent use-after-
    donate into an immediate loud jax deleted-array error.  Leaves the
    program passed through by identity are kept alive."""
    from ..testing import jaxsan as _jaxsan
    if not _jaxsan.enabled():
        return
    old = list(params) + [m for m in masters if m is not None]
    for st in states:
        old.extend(st)
    keep = list(new_p) + list(new_m) + [s for st in new_s for s in st]
    _jaxsan.poison_donated(old, site="optimizer.fused_step", keep=keep)


def try_step(opt, work) -> bool:
    """Fused path for a plain `Optimizer.step` (no scaler).  `work` is
    the collected [param, grad, lr, wd, l1] list; the caller has already
    incremented `_global_step`.  False → run the per-leaf path."""
    if not enabled() or not work:
        return False
    if _rule_of(opt) is None or any(item[4] for item in work):
        # no elementwise rule (LBFGS) / L1 decay: cheap Python checks
        # BEFORE the pre-clip below, so a permanently-unfusible config
        # doesn't pay a wasted clip dispatch (and a double clip) per step
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    clip_key, reduce_fn, clip_ok = _clip_config(opt)
    if not clip_ok:
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    external = clip_key is not None and clip_key[0] in ("norm", "value")
    # plan first — its key depends on avals only, which clipping
    # preserves — so an _UNFUSIBLE tree falls back without paying the
    # pre-clip dispatch every step
    plan = _plan(opt, work, None,
                 (None, None) if external else (clip_key, reduce_fn))
    if plan is None:
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    grads_override = None
    if external:
        # per-leaf clips round at their own program boundary so the
        # per-leaf path's bits are reproducible (in-program, XLA may
        # contract the clip multiply into the update as an fma); the
        # clip object's one cached per-tree program + the clip-free
        # update program is still 2 dispatches
        pairs = opt._grad_clip([(p, g) for p, g, *_ in work])
        for item, (_, g) in zip(work, pairs):
            item[1] = g
        grads_override = [g._value if isinstance(g, Tensor) else g
                          for _, g, *_ in work]
    try:
        _execute(opt, plan, None, grads_override)
    except _PLAN_ERRORS:
        # pre-execution failure (placement validation, untraceable clip
        # hook) — buffers intact: remember and fall back
        opt._fused_programs[plan[1]] = _UNFUSIBLE
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    return True


def scaler_step(scaler, opt) -> bool:
    """Whole `GradScaler.step` as one device program: unscale, found_inf,
    clip, update-or-skip, dynamic scale bookkeeping — found_inf stays on
    device (read back at `scaler._sync_fused_state`).  False → caller
    runs the legacy host-sync path (which may still fuse the update).
    Clipping always runs in-program here (it must see UNSCALED grads,
    and the unscale/found reduction never leaves the program)."""
    if not enabled():
        return False
    if opt._aux_hooks:
        # the legacy path runs aux hooks only when the update actually
        # APPLIES (optimizer.step is skipped on found_inf), which the
        # fused path cannot decide without a host sync — fall back so
        # hook semantics stay identical
        return False
    clip_key, reduce_fn, clip_ok = _clip_config(opt)
    if not clip_ok:
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    work, _ = opt._collect_work()
    if not work:
        return False
    if sum(1 for p in opt._parameter_list
           if p.grad is not None) != len(work):
        # a param holds a grad but is excluded from the update (frozen
        # via stop_gradient): the legacy path still unscales it and
        # feeds it into found_inf — fall back to keep those semantics
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    g0 = work[0][1]
    if isinstance(getattr(g0, "_value", g0), jax.core.Tracer) or \
            isinstance(work[0][0]._value, jax.core.Tracer):
        # inside a to_static trace: committing tracers into the scaler's
        # device state would leak them past the trace.  Decline — the
        # legacy path's bool(found_inf) concretization graph-breaks the
        # capture exactly as before, and the eager re-run fuses normally.
        return False
    plan = _plan(opt, work, scaler, (clip_key, reduce_fn))
    if plan is None:
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    try:
        _execute(opt, plan, scaler)
    except _PLAN_ERRORS:
        # pre-execution failure (placement validation, untraceable clip
        # hook): the legacy host-sync scaler path serves this tree
        opt._fused_programs[plan[1]] = _UNFUSIBLE
        _M_FUSED.inc_key(_K_FALLBACK)
        return False
    return True
