"""Sparse tensor types + creation.

Parity: `python/paddle/sparse/creation.py` (sparse_coo_tensor `:84`,
sparse_csr_tensor `:183`), `paddle/phi/core/sparse_coo_tensor.h:30`.

TPU-native design: a sparse tensor is (indices, values, shape) where the
VALUES are a regular autograd-tracked `Tensor` — every sparse op routes
its value math through the dense op registry, so `loss.backward()`
differentiates through sparse networks exactly like dense ones (the
reference registers separate sparse grad kernels under
`paddle/phi/kernels/sparse/` — here the tape is shared).  The jax BCOO
form is materialized on demand for XLA spmm interop.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.tensor import Tensor

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor"]


def _as_value_tensor(v):
    if isinstance(v, Tensor):
        return v
    return Tensor._wrap(jnp.asarray(np.asarray(v)))


class SparseCooTensor:
    """COO sparse tensor: indices [nnz, sparse_dim] (int32, host-known),
    values Tensor [nnz, *dense_dims]."""

    def __init__(self, indices, values=None, shape=None):
        if isinstance(indices, jsparse.BCOO):  # legacy BCOO ctor path
            bcoo = indices
            self._indices = jnp.asarray(bcoo.indices, jnp.int32)
            self._values = Tensor._wrap(bcoo.data)
            self._shape = tuple(bcoo.shape)
        else:
            idx = jnp.asarray(indices)
            if idx.dtype not in (jnp.int32, jnp.int64):
                idx = idx.astype(jnp.int32)
            self._indices = idx
            self._values = _as_value_tensor(values)
            self._shape = tuple(int(s) for s in shape)

    # -------------------------------------------------------------- views
    @property
    def _bcoo(self) -> jsparse.BCOO:
        return jsparse.BCOO((self._values._value, self._indices),
                            shape=self._shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def sparse_dim(self) -> int:
        return int(self._indices.shape[1])

    @property
    def nnz(self) -> int:
        return int(self._indices.shape[0])

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    @property
    def grad(self):
        return self._values.grad

    def indices(self) -> Tensor:
        # paddle layout: (sparse_dim, nnz); stored (nnz, sparse_dim)
        return Tensor._wrap(self._indices.T)

    def values(self) -> Tensor:
        return self._values

    def to_dense(self) -> Tensor:
        """Differentiable densify: scatter the value TENSOR so gradients
        flow back into values()."""
        from ..ops import creation as _c, manipulation as _m
        dense = _c.zeros(list(self._shape), dtype=str(self.dtype))
        idx = Tensor._wrap(self._indices)
        return _m.scatter_nd_add(dense, idx, self._values)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        c = self.coalesce()
        return SparseCsrTensor(c._indices, c._values, c._shape)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate indices (sums values; differentiable)."""
        idx = np.asarray(self._indices)
        lin = np.ravel_multi_index(
            tuple(idx.T), self._shape[:idx.shape[1]]) if idx.size else \
            np.zeros((0,), np.int64)
        uniq, inv = np.unique(lin, return_inverse=True)
        from ..ops import creation as _c, manipulation as _m
        if len(uniq) == len(lin):
            order = np.argsort(lin, kind="stable")
            vals = _m.gather(self._values,
                             Tensor._wrap(jnp.asarray(order)), axis=0)
            return type(self)(idx[order], vals, self._shape)
        segsum = _c.zeros([len(uniq)] + list(self._values.shape[1:]),
                          dtype=str(self.dtype))
        segsum = _m.scatter_nd_add(
            segsum, Tensor._wrap(jnp.asarray(inv.reshape(-1, 1))),
            self._values)
        new_idx = np.stack(np.unravel_index(
            uniq, self._shape[:idx.shape[1]]), axis=1).astype(np.int32)
        return type(self)(new_idx, segsum, self._shape)

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def _replace(self, values: Tensor) -> "SparseCooTensor":
        # preserves the concrete type: relu(csr) stays CSR
        return type(self)(self._indices, values, self._shape)

    def backward(self, *a, **k):
        return self._values.backward(*a, **k)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor(SparseCooTensor):
    """CSR view: same (indices, values) storage + materialised crows/cols
    on demand.  Parity: `sparse_csr_tensor.h:30`."""

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def crows(self) -> Tensor:
        idx = np.asarray(self._indices)
        rows = idx[:, 0]
        n_rows = self.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        return Tensor._wrap(jnp.asarray(np.cumsum(crows)))

    def cols(self) -> Tensor:
        return Tensor._wrap(self._indices[:, 1])

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) \
            -> SparseCooTensor:
        return SparseCooTensor(self._indices, self._values, self._shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _as_jnp(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(np.asarray(x))


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True) \
        -> SparseCooTensor:
    """Build a COO tensor from (sparse_dim, nnz) indices + values whose
    leading dim is nnz (trailing dims are dense)."""
    idx = _as_jnp(indices).astype(jnp.int32).T  # -> (nnz, sparse_dim)
    vals = _as_value_tensor(values)
    if dtype is not None:
        from ..core import dtypes as _dtypes
        from ..ops import manipulation as _m
        vals = _m.cast(vals, _dtypes.convert_dtype(dtype))
    if shape is None:
        sp = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
        shape = sp + tuple(vals.shape[1:])
    out = SparseCooTensor(idx, vals, shape)
    if not isinstance(values, Tensor):
        # a freshly wrapped array takes the requested flag; a caller's
        # Tensor keeps ITS OWN stop_gradient (mutating it here would
        # silently freeze the tensor everywhere else it is used)
        out.stop_gradient = stop_gradient
    return out


def sparse_csr_tensor(crows, cols, values,
                      shape: Sequence[int], dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    """Build a CSR tensor from compressed rows + cols + values."""
    crows_np = np.asarray(_as_jnp(crows))
    cols_np = np.asarray(_as_jnp(cols))
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np], axis=1).astype(np.int32)
    vals = _as_value_tensor(values)
    if dtype is not None:
        from ..core import dtypes as _dtypes
        from ..ops import manipulation as _m
        vals = _m.cast(vals, _dtypes.convert_dtype(dtype))
    out = SparseCsrTensor(idx, vals, tuple(shape))
    if not isinstance(values, Tensor):
        out.stop_gradient = stop_gradient  # see sparse_coo_tensor
    return out


# Tensor bridge methods (reference: Tensor.to_sparse_coo / to_dense)
def _tensor_to_sparse_coo(self, sparse_dim: int) -> SparseCooTensor:
    bcoo = jsparse.BCOO.fromdense(self._value, n_batch=0,
                                  n_dense=self._value.ndim - sparse_dim)
    return SparseCooTensor(jnp.asarray(bcoo.indices, jnp.int32),
                           Tensor._wrap(bcoo.data), tuple(bcoo.shape))


Tensor.to_sparse_coo = _tensor_to_sparse_coo
