"""Multiprocess DataLoader workers.

Mirrors the reference's `test_multiprocess_dataloader_static/dynamic.py`
strategy: correctness + ordering + error propagation with real spawned
worker processes.
"""

import numpy as np
import pytest

import paddle_tpu as paddle


class SquareDataset(paddle.io.Dataset):
    """Deterministic contents so batch ordering is checkable."""

    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((3,), float(i), np.float32),
                np.int64(i * i))


class FailingDataset(SquareDataset):
    def __getitem__(self, i):
        if i == 7:
            raise ValueError("boom at 7")
        return super().__getitem__(i)


@pytest.mark.parametrize("use_shm", [True, False])
def test_mp_loader_matches_serial(use_shm):
    ds = SquareDataset(32)
    serial = [b for b in paddle.io.DataLoader(ds, batch_size=4,
                                              shuffle=False)]
    parallel = [b for b in paddle.io.DataLoader(
        ds, batch_size=4, shuffle=False, num_workers=2,
        use_shared_memory=use_shm)]
    assert len(parallel) == len(serial) == 8
    for (xs, ys), (xp, yp) in zip(serial, parallel):
        np.testing.assert_array_equal(np.asarray(xs._value),
                                      np.asarray(xp._value))
        np.testing.assert_array_equal(np.asarray(ys._value),
                                      np.asarray(yp._value))


@pytest.mark.slow  # 7s measured (PR 18 re-budget): spawns the worker pool twice; test_mp_loader_matches_serial keeps the fast mp pin
def test_mp_loader_order_is_deterministic():
    ds = SquareDataset(24)
    loader = paddle.io.DataLoader(ds, batch_size=3, shuffle=False,
                                  num_workers=3)
    firsts = [float(np.asarray(x._value)[0, 0]) for x, _ in loader]
    assert firsts == [0.0, 3.0, 6.0, 9.0, 12.0, 15.0, 18.0, 21.0]


def test_mp_loader_propagates_worker_error():
    loader = paddle.io.DataLoader(FailingDataset(16), batch_size=4,
                                  shuffle=False, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 7"):
        list(loader)


def _sum_collate(samples):
    """module-level: spawn workers must pickle the collate_fn"""
    return np.stack([s[0] for s in samples]).sum(axis=1)


def test_mp_loader_custom_collate():
    loader = paddle.io.DataLoader(SquareDataset(8), batch_size=4,
                                  shuffle=False, num_workers=2,
                                  collate_fn=_sum_collate)
    out = [np.asarray(b._value) for b in loader]
    np.testing.assert_allclose(out[0], [0.0, 3.0, 6.0, 9.0])


def test_thread_fallback_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_THREAD_LOADER", "1")
    loader = paddle.io.DataLoader(SquareDataset(8), batch_size=4,
                                  shuffle=False, num_workers=2)
    out = list(loader)
    assert len(out) == 2
