"""Training-step fast path: hapi flag-spaced loss sync, dataloader
device prefetch, and the GradScaler passthrough/counter satellites
(round-7 tentpole acceptance tests beyond the optimizer parity suite in
test_optimizer.py)."""

import gc
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.flags import flag_guard
from paddle_tpu.observability import flight_recorder as flight
from paddle_tpu.observability import metrics as obs


@pytest.fixture(autouse=True)
def _clean_observability_state():
    """Deterministic telemetry/counter state per test (same convention as
    test_telemetry): the default timeline's step indices restart at 0."""
    from paddle_tpu.observability import telemetry
    obs.reset()
    flight.default_recorder().clear()
    telemetry.default_timeline().reset()
    yield
    paddle.set_flags({"enable_metrics": True, "enable_nan_watchdog": False,
                      "flight_dump_dir": ""})
    obs.reset()
    flight.default_recorder().clear()
    telemetry.default_timeline().reset()


class _BlobDataset(paddle.io.Dataset):
    def __init__(self, n=32, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.rand(n, 4).astype(np.float32)
        self.y = self.x.sum(axis=1, keepdims=True).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _prepared_model(lr=0.01):
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.Adam(learning_rate=lr,
                                           parameters=net.parameters()),
                  loss=nn.MSELoss())
    return model


# ------------------------------------------------------- loss sync interval
def _loss_syncs():
    c = obs.get("train.loss_syncs")
    return c.total() if c else 0


@pytest.mark.parametrize("interval,steps", [(1, 8), (3, 8), (4, 8), (5, 8)])
def test_loss_sync_interval_host_read_count(interval, steps):
    """With FLAGS_loss_sync_interval=K, fit performs exactly ceil(steps/K)
    host reads of the loss (asserted by the train.loss_syncs counter)."""
    with flag_guard(loss_sync_interval=interval, enable_metrics=True):
        model = _prepared_model()
        before = _loss_syncs()
        model.fit(_BlobDataset(32), batch_size=4, epochs=1, verbose=0,
                  shuffle=False)
        reads = _loss_syncs() - before
    assert reads == -(-steps // interval), \
        f"K={interval}: {reads} host reads for {steps} steps"


def test_loss_sync_interval_resets_per_fit():
    """Each fit() restarts the sync phase: step 0 always syncs (logs
    carry a 'loss' from the first callback) and every fit performs its
    own ceil(steps/K) host reads — the cadence must not bleed across
    fit() calls."""
    with flag_guard(loss_sync_interval=4, enable_metrics=True):
        model = _prepared_model()
        model.fit(_BlobDataset(8), batch_size=4, epochs=1, verbose=0,
                  shuffle=False)  # 2 steps -> 1 read, phase now mid-K
        before = _loss_syncs()
        logs = model.fit(_BlobDataset(8), batch_size=4, epochs=1,
                         verbose=0, shuffle=False)
    assert "loss" in logs
    assert _loss_syncs() - before == 1  # ceil(2/4)


def test_loss_sync_interval_unsynced_batch_returns_device_array():
    import jax
    with flag_guard(loss_sync_interval=3):
        model = _prepared_model()
        x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        synced, _ = model.train_batch([x], [y])       # step 0: synced
        deferred, _ = model.train_batch([x], [y])     # step 1: on device
        assert isinstance(synced, np.ndarray)
        assert not isinstance(deferred, np.ndarray)
        assert isinstance(deferred, jax.Array)
        # the device handle still materializes to a finite loss on demand
        assert np.isfinite(float(np.asarray(deferred).reshape(-1)[0]))


def test_loss_sync_records_mark_synced_steps_only():
    from paddle_tpu.observability import telemetry
    with flag_guard(loss_sync_interval=2, enable_metrics=True):
        model = _prepared_model()
        tl = telemetry.default_timeline()
        n0 = len(tl.records)
        x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True)
        for _ in range(4):
            model.train_batch([x], [y])
        recs = tl.records[n0:]
    assert [r["synced"] for r in recs] == [True, False, True, False]
    assert [r["loss"] is not None for r in recs] == \
        [True, False, True, False]
    # async attribution: the summary separates synced from enqueue-time
    # steps so throughput readers see how many walls are trustworthy
    assert tl.summary()["synced_steps"] == 2


def test_nan_watchdog_names_synced_step_with_interval(tmp_path):
    """Acceptance: with K-spaced syncs the flight recorder still names
    the step whose (synced) loss went non-finite."""

    class NanAfter(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 1)
            self.calls = 0

        def forward(self, x):
            self.calls += 1
            out = self.lin(x)
            if self.calls > 3:
                out = out * paddle.to_tensor(np.float32(np.nan))
            return out

    net = NanAfter()
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.0,
                                          parameters=net.parameters()),
                  loss=nn.MSELoss(), jit_compile=False)
    x = np.random.RandomState(0).rand(4, 4).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    rec = flight.default_recorder()
    with flag_guard(loss_sync_interval=2, enable_nan_watchdog=True,
                    enable_metrics=True, flight_dump_dir=str(tmp_path)):
        for _ in range(6):
            model.train_batch([x], [y])
    assert rec.first_nonfinite is not None
    # NaN first appears at step index 3 (unsynced); the first probed loss
    # carrying it is synced step 4 — the recorder must name THAT step
    assert rec.first_nonfinite["site"] == "hapi.train.loss"
    assert rec.first_nonfinite["step"] == 4


# --------------------------------------------------- dataloader device prefetch
def _batch_values(loader):
    out = []
    for batch in loader:
        out.append(tuple(np.asarray(b._value) for b in batch))
    return out


def test_device_prefetch_batch_parity():
    """Same batch sequence and values with the flag on and off."""
    ds = _BlobDataset(20, seed=3)
    with flag_guard(dataloader_device_prefetch=False):
        ref = _batch_values(paddle.io.DataLoader(ds, batch_size=3,
                                                 shuffle=False))
    with flag_guard(dataloader_device_prefetch=True):
        got = _batch_values(paddle.io.DataLoader(ds, batch_size=3,
                                                 shuffle=False))
    assert len(ref) == len(got) == 7
    for a, b in zip(ref, got):
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def test_device_prefetch_batches_are_device_arrays():
    import jax
    with flag_guard(dataloader_device_prefetch=True):
        loader = paddle.io.DataLoader(_BlobDataset(8), batch_size=4)
        for batch in loader:
            for t in batch:
                assert isinstance(t._value, jax.Array)


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name == "paddle-tpu-device-prefetch"]


def test_device_prefetch_abandoned_iterator_no_leaked_thread():
    with flag_guard(dataloader_device_prefetch=True):
        loader = paddle.io.DataLoader(_BlobDataset(32), batch_size=2)
        it = iter(loader)
        next(it)
        next(it)
        it.close()  # abandon mid-epoch
        gc.collect()
        deadline = 50
        while _prefetch_threads() and deadline:
            import time
            time.sleep(0.05)
            deadline -= 1
        assert not _prefetch_threads(), "prefetch thread leaked"

        # a fresh epoch over the same loader still yields every batch
        assert len(list(loader)) == 16


def test_device_prefetch_propagates_dataset_errors():
    class Boom(paddle.io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i >= 4:
                raise RuntimeError("boom at 4")
            return np.float32(i)

    with flag_guard(dataloader_device_prefetch=True):
        loader = paddle.io.DataLoader(Boom(), batch_size=2)
        with pytest.raises(RuntimeError, match="boom"):
            list(loader)
    assert not _prefetch_threads()


# ------------------------------------------------------------ scaler satellites
def test_disabled_scaler_is_strict_passthrough():
    """enable=False: no unscale, no found probe, no amp.found_inf count —
    the step just runs."""
    with flag_guard(enable_metrics=True):
        c = obs.get("amp.found_inf")
        before = c.total() if c else 0
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(enable=False)
        p.grad = paddle.to_tensor([1.0, 1.0])
        scaler.step(opt)
        np.testing.assert_allclose(p.numpy(), [0.9, 0.9], rtol=1e-6)
        c = obs.get("amp.found_inf")
        assert (c.total() if c else 0) == before
        assert scaler._dev_state is None  # no device bookkeeping either


def test_found_inf_counter_outcomes_eager():
    with flag_guard(fused_optimizer=False, enable_metrics=True):
        c = obs.counter("amp.found_inf")
        ok0, sk0 = c.value(outcome="ok"), c.value(outcome="skipped")
        p = paddle.Parameter(np.ones(1, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=4.0)
        p.grad = paddle.to_tensor([4.0])
        scaler.step(opt)
        p.grad = paddle.to_tensor([np.inf])
        scaler.step(opt)
        assert c.value(outcome="ok") == ok0 + 1
        assert c.value(outcome="skipped") == sk0 + 1


def test_found_inf_counter_outcomes_fused_accounted_at_sync():
    """Fused steps keep found_inf on device; the per-step outcomes land
    on the counter in bulk at the next host sync."""
    with flag_guard(fused_optimizer=True, enable_metrics=True):
        c = obs.counter("amp.found_inf")
        ok0, sk0 = c.value(outcome="ok"), c.value(outcome="skipped")
        p = paddle.Parameter(np.ones(3, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=4.0)
        for g in ([4.0, 4.0, 4.0], [np.inf, 0.0, 0.0], [4.0, 4.0, 4.0]):
            p.grad = paddle.to_tensor(np.asarray(g, np.float32))
            scaler.step(opt)
        assert scaler._dev_state is not None  # still deferred
        assert c.value(outcome="ok") == ok0
        scaler._sync_fused_state()
        assert c.value(outcome="ok") == ok0 + 2
        assert c.value(outcome="skipped") == sk0 + 1
        assert scaler._scale == 2.0  # one overflow halved 4.0


def test_fused_scaler_step_defers_host_sync():
    """The fused scaler path must not materialize found_inf on the host:
    the device state stays live across steps until explicitly synced."""
    with flag_guard(fused_optimizer=True):
        p = paddle.Parameter(np.ones(4, np.float32))
        opt = optimizer.Adam(learning_rate=0.01, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=8.0)
        for _ in range(3):
            p.grad = paddle.to_tensor(np.full(4, 8.0, np.float32))
            scaler.step(opt)
            assert scaler._dev_state is not None
        assert scaler._steps_since_sync == 3
        scaler._sync_fused_state()
        assert scaler._steps_since_sync == 0
        assert scaler._dev_state is None


def test_fused_scaler_step_leaves_grads_unscaled():
    """Legacy parity: after scaler.step() the grads a user inspects are
    UNSCALED (the _unscale_and_check contract) on both paths."""
    def run(fused):
        with flag_guard(fused_optimizer=fused):
            p = paddle.Parameter(np.ones(3, np.float32))
            opt = optimizer.SGD(learning_rate=0.0, parameters=[p])
            scaler = amp.GradScaler(init_loss_scaling=1024.0)
            p.grad = paddle.to_tensor(np.full(3, 1024.0, np.float32))
            scaler.step(opt)
            return np.asarray(p.grad._value)
    np.testing.assert_array_equal(run(False), [1.0, 1.0, 1.0])
    np.testing.assert_array_equal(run(True), [1.0, 1.0, 1.0])


def test_scaler_state_dict_syncs_fused_state():
    with flag_guard(fused_optimizer=True):
        p = paddle.Parameter(np.ones(2, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = amp.GradScaler(init_loss_scaling=4.0,
                                incr_every_n_steps=2)
        for _ in range(2):
            p.grad = paddle.to_tensor([4.0, 4.0])
            scaler.step(opt)
        sd = scaler.state_dict()  # forces the sync
    assert sd["scale"] == 8.0  # two good steps -> one increase
    assert sd["good_steps"] == 0


def test_hapi_scaler_fit_with_loss_sync_interval_learns():
    """End-to-end: AMP-scaled hapi fit with fused optimizer, K-spaced
    loss sync and device prefetch all on — the loss must still go down
    and the scaler state must stay consistent."""
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    model = paddle.Model(net)
    model.prepare(
        optimizer=optimizer.Adam(learning_rate=0.05,
                                 parameters=net.parameters()),
        loss=nn.MSELoss(),
        amp_configs={"level": "O1", "init_loss_scaling": 256.0})
    assert model._scaler is not None
    with flag_guard(loss_sync_interval=3, fused_optimizer=True,
                    dataloader_device_prefetch=True):
        logs = model.fit(_BlobDataset(64, seed=1), batch_size=8, epochs=6,
                         verbose=0, shuffle=False)
    assert logs["loss"] < 0.1, logs
    # reading the scale syncs any pending fused device state
    assert model._scaler._scale >= 1.0
    assert model._scaler._dev_state is None
