"""Fused incubate functionals.

Parity: `python/paddle/incubate/nn/functional/` — fused_rotary_position_
embedding (ref `fused_rope_kernel.cu`), fused_rms_norm, fused_layer_norm,
swiglu.  On TPU these are single fused XLA expressions (+ Pallas variants for
the attention path); XLA's fusion makes the "fused" prefix literal."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.tensor import Tensor
from ....ops.registry import dispatch as _d, register_op
from ....nn.functional.norm import rms_norm as fused_rms_norm  # noqa: F401
from ....nn.functional.norm import layer_norm as fused_layer_norm  # noqa: F401

from .ring_attention import (  # noqa: F401,E402
    ring_attention, ring_attention_local, ring_attention_chunked,
    ulysses_attention, ulysses_attention_local)

__all__ = ["ring_attention", "ring_attention_local",
           "ring_attention_chunked", "ulysses_attention",
           "ulysses_attention_local",
           "fused_rotary_position_embedding", "rope", "swiglu",
           "fused_rms_norm", "fused_layer_norm", "fused_bias_act",
           "fused_linear", "fused_multi_head_attention",
           "fused_feedforward", "fused_dropout_add",
           "fused_bias_dropout_residual_layer_norm",
           "block_multihead_attention", "BlockKVCache"]


def _rope_impl(q, k, v, cos, sin, *, use_neox):
    def rot(x):
        if x is None:
            return None
        # x: [B, S, H, D]
        if use_neox:
            x1, x2 = jnp.split(x, 2, axis=-1)
            rx = jnp.concatenate([-x2, x1], axis=-1)
        else:
            x1 = x[..., 0::2]
            x2 = x[..., 1::2]
            rx = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
        return x * cos + rx * sin
    return tuple(r for r in (rot(q), rot(k), rot(v)) if r is not None) \
        if (k is not None or v is not None) else rot(q)


register_op("fused_rope", _rope_impl, tags=("fused",))


def _default_cos_sin(seq_len, head_dim, dtype, use_neox, base=10000.0):
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                          / head_dim))
    freqs = jnp.outer(pos, inv)  # [S, D/2]
    if use_neox:
        emb = jnp.concatenate([freqs, freqs], axis=-1)
    else:
        emb = jnp.repeat(freqs, 2, axis=-1)
    return (jnp.cos(emb)[None, :, None, :].astype(dtype),
            jnp.sin(emb)[None, :, None, :].astype(dtype))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity;
    layout [batch, seq, heads, head_dim]."""
    if cos is None or sin is None:
        if position_ids is not None:
            # decode-time offsets: rotate by the tokens' absolute positions;
            # accepts (S,) or the reference's (B, S) per-row id matrix.
            # Angles come straight from pids ⊗ inv_freq (identical to the
            # reference's table lookup) so TRACED positions work — compiled
            # decode loops pass the offset as a scalar program input
            pids = position_ids._value if isinstance(position_ids, Tensor) \
                else jnp.asarray(position_ids)
            hd = q.shape[-1]
            inv = 1.0 / (rotary_emb_base
                         ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
            freqs = pids.astype(jnp.float32)[..., None] * inv  # (..., D/2)
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            dtype = q._value.dtype
            if pids.ndim == 1:
                cos_v = jnp.cos(emb)[None, :, None, :].astype(dtype)
                sin_v = jnp.sin(emb)[None, :, None, :].astype(dtype)
            else:  # (B, S): per-row positions
                cos_v = jnp.cos(emb)[:, :, None, :].astype(dtype)
                sin_v = jnp.sin(emb)[:, :, None, :].astype(dtype)
        else:
            cos_v, sin_v = _default_cos_sin(
                q.shape[1], q.shape[-1], q._value.dtype,
                use_neox_rotary_style, rotary_emb_base)
        cos = Tensor._wrap(cos_v)
        sin = Tensor._wrap(sin_v)
    outs = _d("fused_rope", (q, k, v, cos, sin),
              {"use_neox": bool(use_neox_rotary_style)})
    if isinstance(outs, tuple):
        res = list(outs)
        while len(res) < 3:
            res.append(None)
        return tuple(res[:3])
    return outs, None, None


rope = fused_rotary_position_embedding

register_op("swiglu", lambda x, y: jax.nn.silu(x) * y if y is not None
            else _swiglu_single(x), tags=("fused",))


def _swiglu_single(x):
    a, b = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(a) * b


def swiglu(x, y=None, name=None):
    return _d("swiglu", (x, y), {})


register_op("fused_bias_act", lambda x, bias, *, act:
            getattr(jax.nn, act)(x + bias if bias is not None else x),
            tags=("fused",))


def fused_bias_act(x, bias=None, act_method="gelu", name=None, **kw):
    act = {"gelu": "gelu", "relu": "relu", "silu": "silu",
           "swiglu": "silu"}.get(act_method, act_method)
    return _d("fused_bias_act", (x, bias), {"act": act})


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ....nn import functional as F
    from ....ops.linalg import matmul
    if transpose_weight:
        return matmul(x, weight, transpose_y=True) + (bias if bias is not None
                                                      else 0.0)
    return F.linear(x, weight, bias)


def _fused_ln(v, scale, bias, eps):
    """LayerNorm helper shared by the fused blocks."""
    mu = jnp.mean(v, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(v - mu), axis=-1, keepdims=True)
    out = (v - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def _fused_drop(v, rate, tag, *, training, mode, seed):
    """Mode-aware dropout shared by the fused blocks.  p=1.0 drops
    everything (no 0/0); upscale_in_train scales kept values at train
    time, downscale_in_infer scales by keep prob at infer time."""
    if rate <= 0.0:
        return v
    if not training:
        return v * (1.0 - rate) if mode == "downscale_in_infer" else v
    keep = jax.random.bernoulli(jax.random.fold_in(seed, tag),
                                1.0 - rate, v.shape)
    kept = jnp.where(keep, v, 0.0)
    if mode == "downscale_in_infer":
        return kept
    return kept / max(1.0 - rate, 1e-12)


# fused activations follow the REPO's op semantics (erf gelu by default,
# matching nn.functional.gelu / the reference), not jax.nn defaults
_FUSED_ACTS = {
    "relu": jax.nn.relu,
    "gelu": lambda v: jax.nn.gelu(v, approximate=False),
    "silu": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
}


def _check_dropout_args(mode, *rates):
    if mode not in ("upscale_in_train", "downscale_in_infer"):
        raise ValueError(f"unknown dropout mode {mode!r}")
    for r in rates:
        if not 0.0 <= r <= 1.0:
            raise ValueError(f"dropout rate {r} outside [0, 1]")



def _fused_mha_impl(x, qkv_weight, qkv_bias, linear_weight, linear_bias,
                    pre_ln_scale, pre_ln_bias, ln_scale, ln_bias,
                    attn_mask, *, pre_layer_norm, pre_ln_epsilon,
                    ln_epsilon, dropout_rate, attn_dropout_rate,
                    training, add_residual, num_heads, transpose_qkv_wb,
                    mode, seed):
    B, S, H = x.shape
    residual = x
    _ln = _fused_ln

    def _drop(v, rate, tag):
        return _fused_drop(v, rate, tag, training=training, mode=mode,
                           seed=seed)

    h = _ln(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon) \
        if pre_layer_norm else x
    if transpose_qkv_wb:
        nh = num_heads
        qkv = h @ qkv_weight                       # [B, S, 3H]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkv = qkv.reshape(B, S, 3, nh, H // nh)
    else:
        # qkv_weight [3, nh, hd, H]
        _, nh, hd, _ = qkv_weight.shape
        qkv = jnp.einsum("bsh,cndh->bscnd", h, qkv_weight)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias[None, None]       # bias [3, nh, hd]
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)         # [B, nh, S, hd]
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    hd = q.shape[-1]
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k,
                   preferred_element_type=jnp.float32) \
        * (hd ** -0.5)
    if attn_mask is not None:
        s = s + attn_mask
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    p = _drop(p, attn_dropout_rate, 1)
    out = jnp.einsum("bnqk,bnkd->bnqd", p, v)
    out = out.transpose(0, 2, 1, 3).reshape(B, S, -1)
    out = out @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    out = _drop(out, dropout_rate, 2)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = _ln(out, ln_scale, ln_bias, ln_epsilon)
    return out


register_op("fused_multi_head_attention", _fused_mha_impl,
            tags=("mxu", "fused"))


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """paddle.incubate.nn.functional.fused_multi_head_attention parity
    (ref `fused_transformer.py:502` / `fused_attention_op.cu`): the
    fused pre/post-LN self-attention block — on TPU one traced
    expression XLA fuses end to end.  `cache_kv` decoding uses
    nn.MultiHeadAttention's cache path or the paged serving engine."""
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv: use "
            "nn.MultiHeadAttention's cache or inference.ServingEngine")
    _check_dropout_args(mode, dropout_rate, attn_dropout_rate)
    # draw a key ONLY when dropout actually fires (the sdpa convention:
    # a key in the statics would defeat the cached-program fast path and
    # advance the global stream during eval)
    seed = None
    if training and (dropout_rate > 0 or attn_dropout_rate > 0):
        from ....framework import random as _random
        seed = _random.next_key()
    return _d("fused_multi_head_attention",
              (x, qkv_weight, qkv_bias, linear_weight, linear_bias,
               pre_ln_scale, pre_ln_bias, ln_scale, ln_bias, attn_mask),
              {"pre_layer_norm": bool(pre_layer_norm),
               "pre_ln_epsilon": float(pre_ln_epsilon),
               "ln_epsilon": float(ln_epsilon),
               "dropout_rate": float(dropout_rate),
               "attn_dropout_rate": float(attn_dropout_rate),
               "training": bool(training),
               "add_residual": bool(add_residual),
               "num_heads": int(num_heads),
               "transpose_qkv_wb": bool(transpose_qkv_wb),
               "mode": mode,
               "seed": seed})


def block_multihead_attention(q, k_cache, v_cache, block_tables, seq_lens,
                              name=None):
    """Paged-KV decode attention (reference
    `incubate/nn/functional/block_multihead_attention.py` /
    `block_multi_head_attention_kernel.cu`): q [B, nh, hd] against a
    block-paged cache [nh, num_blocks, bs, hd] — a Pallas kernel whose
    block-table gather rides the DMA index_map (`ops/pallas_paged.py`).

    Accepts/returns framework Tensors; raw jax arrays pass through.
    """
    raw = [x._value if isinstance(x, _Tensor) else x
           for x in (q, k_cache, v_cache, block_tables, seq_lens)]
    out = _paged_attention(*raw)
    return _Tensor._wrap(out) if isinstance(q, _Tensor) else out


from ....framework.tensor import Tensor as _Tensor  # noqa: E402
from ....ops.pallas_paged import (  # noqa: E402,F401
    BlockKVCache, paged_attention as _paged_attention)


def _fused_ffn_impl(x, w1, b1, w2, b2, ln1_s, ln1_b, ln2_s, ln2_b, *,
                    pre_layer_norm, ln1_epsilon, ln2_epsilon,
                    dropout1_rate, dropout2_rate, activation, training,
                    add_residual, mode, seed):
    residual = x
    _ln = _fused_ln

    def _drop(v, rate, tag):
        return _fused_drop(v, rate, tag, training=training, mode=mode,
                           seed=seed)

    h = _ln(x, ln1_s, ln1_b, ln1_epsilon) if pre_layer_norm else x
    h = h @ w1
    if b1 is not None:
        h = h + b1
    h = _FUSED_ACTS.get(activation, getattr(jax.nn, activation))(h)
    h = _drop(h, dropout1_rate, 1)
    h = h @ w2
    if b2 is not None:
        h = h + b2
    h = _drop(h, dropout2_rate, 2)
    out = residual + h if add_residual else h
    if not pre_layer_norm:
        out = _ln(out, ln2_s, ln2_b, ln2_epsilon)
    return out


register_op("fused_feedforward", _fused_ffn_impl, tags=("mxu", "fused"))


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """paddle.incubate.nn.functional.fused_feedforward parity (ref
    fused_transformer.py:36): the fused pre/post-LN MLP block —
    linear2(dropout1(act(linear1(ln?(x))))) + residual + (post-)LN."""
    _check_dropout_args(mode, dropout1_rate, dropout2_rate)
    seed = None
    if training and (dropout1_rate > 0 or dropout2_rate > 0):
        from ....framework import random as _random
        seed = _random.next_key()
    return _d("fused_feedforward",
              (x, linear1_weight, linear1_bias, linear2_weight,
               linear2_bias, ln1_scale, ln1_bias, ln2_scale, ln2_bias),
              {"pre_layer_norm": bool(pre_layer_norm),
               "ln1_epsilon": float(ln1_epsilon),
               "ln2_epsilon": float(ln2_epsilon),
               "dropout1_rate": float(dropout1_rate),
               "dropout2_rate": float(dropout2_rate),
               "activation": activation, "training": bool(training),
               "add_residual": bool(add_residual), "mode": mode,
               "seed": seed})


def _fused_dropout_add_impl(x, y, *, p, training, mode, seed):
    return _fused_drop(x, p, 0, training=training, mode=mode,
                       seed=seed) + y


register_op("fused_dropout_add", _fused_dropout_add_impl, tags=("fused",))


def fused_dropout_add(x, y, p=0.5, training=True,
                      mode="upscale_in_train", name=None):
    """paddle.incubate.nn.functional.fused_dropout_add parity
    (ref `incubate/nn/functional/fused_dropout_add.py`):
    dropout(x) + y as one fused expression."""
    _check_dropout_args(mode, p)
    seed = None
    if training and p > 0:
        from ....framework import random as _random
        seed = _random.next_key()
    return _d("fused_dropout_add", (x, y),
              {"p": float(p), "training": bool(training), "mode": mode,
               "seed": seed})


def _fused_bdrln_impl(x, residual, bias, ln_scale, ln_bias, *,
                      dropout_rate, ln_epsilon, training, mode, seed):
    h = x if bias is None else x + bias
    out = residual + _fused_drop(h, dropout_rate, 0, training=training,
                                 mode=mode, seed=seed)
    return _fused_ln(out, ln_scale, ln_bias, ln_epsilon)


register_op("fused_bias_dropout_residual_layer_norm", _fused_bdrln_impl,
            tags=("fused",))


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """paddle.incubate.nn.functional.fused_bias_dropout_residual_layer_norm
    parity (ref fused_transformer.py): ln(residual + dropout(x + bias)),
    one dispatched op (AMP/NaN/profiler hooks apply under its name)."""
    _check_dropout_args(mode, dropout_rate)
    seed = None
    if training and dropout_rate > 0:
        from ....framework import random as _random
        seed = _random.next_key()
    return _d("fused_bias_dropout_residual_layer_norm",
              (x, residual, bias, ln_scale, ln_bias),
              {"dropout_rate": float(dropout_rate),
               "ln_epsilon": float(ln_epsilon),
               "training": bool(training), "mode": mode, "seed": seed})
