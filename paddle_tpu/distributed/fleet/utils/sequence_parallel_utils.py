"""Sequence-parallel utilities (Megatron SP).

Parity: `python/paddle/distributed/fleet/utils/sequence_parallel_utils.py` —
scatter (`:42`), all_gather (`:58`), reduce_scatter (`:69`), ScatterOp
(`:85`), GatherOp (`:97`), AllGatherOp (`:111`), ReduceScatterOp (`:127`),
ColumnSequenceParallelLinear (`:395`), RowSequenceParallelLinear (`:528`),
mark/is_sequence_parallel_parameter (`:148`).

TPU-native: the reference implements each op as a PyLayer whose forward and
backward issue explicit NCCL calls.  Here the ops are *sharding moves*: in
eager they are device_puts to the target NamedSharding; under jit they are
`with_sharding_constraint`s that GSPMD lowers to the identical all-gather /
reduce-scatter pairs — and to their transposes in the backward pass
automatically (the adjoint of all-gather IS reduce-scatter, which is why the
reference had to hand-write both directions).  The sequence axis rides the
'mp' mesh axis, exactly like the reference reuses the TP group for SP.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework.tensor import Tensor
from ....nn.layer.layers import Layer
from ....ops.registry import dispatch as _dispatch, register_op
from .. import mp_layers as _mp
from ... import mesh as _mesh

__all__ = ["scatter", "all_gather", "reduce_scatter",
           "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "is_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


def _base_entries(value, ndim: int):
    """Per-dim spec entries preserving the value's existing sharding on all
    dims we don't touch (so a dp-sharded batch dim stays dp-sharded).
    Tracers have no readable sharding — leave other dims UNCONSTRAINED for
    GSPMD to propagate."""
    if isinstance(value, jax.core.Tracer):
        unconstrained = getattr(P, "UNCONSTRAINED", None)
        return [unconstrained] * ndim
    sh = getattr(value, "sharding", None)
    if isinstance(sh, NamedSharding) and len(sh.spec) <= ndim:
        entries = list(sh.spec) + [None] * (ndim - len(sh.spec))
        return entries
    return [None] * ndim


def _mesh_or_raise():
    m = _mesh.get_mesh()
    if m is None:
        raise RuntimeError("sequence parallel needs fleet.init / a global "
                           "mesh (distributed.mesh.set_mesh)")
    return m


def _strip_axis(entries, axis_name):
    """A mesh axis may appear in at most one spec entry."""
    out = []
    for e in entries:
        if e == axis_name:
            out.append(None)
        elif isinstance(e, tuple) and axis_name in e:
            rest = tuple(x for x in e if x != axis_name)
            out.append(rest if rest else None)
        else:
            out.append(e)
    return out


def _seq_sharding(value, seq_axis: int, axis_name: str = "mp"):
    ndim = value.ndim
    entries = _strip_axis(_base_entries(value, ndim), axis_name)
    entries[seq_axis] = axis_name
    return NamedSharding(_mesh_or_raise(), P(*entries))


def _replicated(value, seq_axis: int, axis_name: str = "mp"):
    ndim = value.ndim
    entries = _base_entries(value, ndim)
    entries[seq_axis] = None
    return NamedSharding(_mesh_or_raise(), P(*entries))


def _move(value, sharding=None):
    if isinstance(value, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(value, sharding)
    return jax.device_put(value, sharding)


# registered so the eager tape differentiates through the move (the adjoint
# of a sharding move is a sharding move — jax.vjp of device_put handles it)
register_op("sp_sharding_move", _move)


def _apply_move(input, sharding):
    if isinstance(input, Tensor):
        return _dispatch("sp_sharding_move", (input,),
                         {"sharding": sharding})
    return _move(input, sharding)


def scatter(input, axis: int = 0, axis_name: str = "mp"):
    """Split the sequence dim over the SP group (reference `:42`)."""
    v = input._value if isinstance(input, Tensor) else input
    return _apply_move(input, _seq_sharding(v, axis, axis_name))


def all_gather(input, axis: int = 0, axis_name: str = "mp"):
    """Reassemble the full sequence on every rank (reference `:58`)."""
    v = input._value if isinstance(input, Tensor) else input
    return _apply_move(input, _replicated(v, axis, axis_name))


def reduce_scatter(input, axis: int = 0, axis_name: str = "mp"):
    """Sum partial activations and shard the sequence dim (reference `:69`).

    In the GSPMD formulation the partial-sum enters as a replicated-but-
    partial value only inside a manual shard_map; at the user API level the
    op is the sharding move whose lowering is the reduce-scatter.
    """
    return scatter(input, axis, axis_name)


# Layer aliases matching the reference's PyLayer names ----------------------
class _OpModule:
    """Reference exposes ScatterOp.apply(x); keep that call shape."""

    def __init__(self, fn):
        self._fn = fn

    def apply(self, x, *a, **k):
        return self._fn(x, *a, **k)

    def __call__(self, x, *a, **k):
        return self._fn(x, *a, **k)


ScatterOp = _OpModule(scatter)
GatherOp = _OpModule(all_gather)
AllGatherOp = _OpModule(all_gather)
ReduceScatterOp = _OpModule(reduce_scatter)


_sp_params = None


def _sp_registry():
    global _sp_params
    if _sp_params is None:
        import weakref
        # id-keyed (Tensor __eq__ is elementwise, so no WeakSet); entries
        # vanish with the parameter, so a recycled id cannot false-positive
        _sp_params = weakref.WeakValueDictionary()
    return _sp_params


def mark_as_sequence_parallel_parameter(parameter):
    _sp_registry()[id(parameter)] = parameter


def is_sequence_parallel_parameter(parameter):
    return _sp_registry().get(id(parameter)) is parameter


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse_allreduce=False):
    """Reference `:192`: allreduce SP params' grads over the mp group.

    Under GSPMD the gradient of a replicated parameter used by sharded
    activations is already all-reduced by sharding propagation; this hook
    exists for API parity and asserts the marked params are replicated.
    """
    for p in layer.parameters():
        if is_sequence_parallel_parameter(p):
            sh = getattr(p._value, "sharding", None)
            if sh is not None and not sh.is_fully_replicated:
                raise ValueError(
                    f"sequence-parallel parameter {p.name} must be "
                    "replicated; got sharding "f"{sh}")


class ColumnSequenceParallelLinear(_mp.ColumnParallelLinear):
    """Column-parallel linear whose input arrives sequence-sharded.

    Parity: reference `:395`.  The input is all-gathered along the sequence
    (sharding move to replicated), then the column-parallel matmul runs —
    GSPMD fuses the gather into the matmul schedule.
    """

    def forward(self, x):
        x = all_gather(x, axis=1 if x.ndim >= 3 else 0)
        return super().forward(x)


class RowSequenceParallelLinear(_mp.RowParallelLinear):
    """Row-parallel linear whose output leaves sequence-sharded.

    Parity: reference `:528`.  The row-parallel partial sums are combined
    and immediately scattered along the sequence: one reduce-scatter
    instead of the reference's allreduce-then-split.
    """

    def forward(self, x):
        out = super().forward(x)
        return reduce_scatter(out, axis=1 if out.ndim >= 3 else 0)
