"""Observability dump CLI.

    python -m paddle_tpu.observability.dump             # newest flight dump
    python -m paddle_tpu.observability.dump --dir prof/ # search there
    python -m paddle_tpu.observability.dump --registry  # live registry
    python -m paddle_tpu.observability.dump --prom      # Prometheus text
    python -m paddle_tpu.observability.dump --compile-report
    python -m paddle_tpu.observability.dump --xray      # X-ray ledger
    python -m paddle_tpu.observability.dump --chrome    # chrome trace
    python -m paddle_tpu.observability.dump --fleet-trace d0 d1 d2
                                        # merged multi-process timeline

Prints ONE JSON document on stdout (``--prom`` prints Prometheus text
exposition instead — the same bytes the /metrics endpoint serves).  Default mode locates the newest
``flight_*.json`` written by the flight recorder (automatic NaN/hang/
exception dumps or ``bench.py`` failure artifacts) in ``--dir`` (falls
back to ``FLAGS_flight_dump_dir``, then the cwd) and echoes it;
``--registry`` instead snapshots THIS process's metrics registry — which
for a fresh CLI process shows the instruments import-time wiring creates,
so it doubles as a smoke check that the registry imports cleanly.

Exit codes: 0 = document printed, 1 = no dump found (the reason goes to
stderr so stdout stays machine-readable).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional


def find_latest_dump(directory: str) -> Optional[str]:
    """Newest flight_*.json by mtime (dump counters are per-process, so
    name order is not time order across runs)."""
    paths = glob.glob(os.path.join(directory, "flight_*.json"))
    paths += glob.glob(os.path.join(directory, "*.flight.*.json"))
    if not paths:
        return None
    return max(paths, key=lambda p: (os.path.getmtime(p), p))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default=None,
                   help="directory to search for flight dumps "
                        "(default: FLAGS_flight_dump_dir, then cwd)")
    p.add_argument("--registry", action="store_true",
                   help="print this process's metrics registry snapshot "
                        "instead of a flight dump")
    p.add_argument("--prom", action="store_true",
                   help="print this process's registry in Prometheus "
                        "text exposition format (what /metrics serves)")
    p.add_argument("--compile-report", action="store_true",
                   help="print this process's compile tracker report "
                        "(top compilers, recompile blame) as JSON")
    p.add_argument("--xray", action="store_true",
                   help="print this process's engine X-ray report as "
                        "JSON: per-program dispatches / sampled device "
                        "seconds / cost-analysis FLOPs / MFU, top "
                        "programs by cumulative device time, and the "
                        "HLO kernel-coverage table")
    p.add_argument("--chrome", action="store_true",
                   help="convert the located flight dump (newest in "
                        "--dir, or --path) to chrome://tracing JSON on "
                        "stdout: the tick timeline with its phase "
                        "breakdown + one row per request lifecycle")
    p.add_argument("--fleet-trace", nargs="+", default=None,
                   metavar="DIR_OR_FILE",
                   help="merge one flight dump per fleet process "
                        "(router first, then replicas; each operand is "
                        "a dump file or a directory searched like --dir) "
                        "into ONE chrome://tracing JSON on stdout — "
                        "replica clocks are aligned to the router's via "
                        "the recorded clock_sync offsets")
    p.add_argument("--path", default=None,
                   help="print this exact dump file (skips the search)")
    args = p.parse_args(argv)

    if args.fleet_trace:
        from . import tracing
        docs = []
        for operand in args.fleet_trace:
            path = operand
            if os.path.isdir(operand):
                path = find_latest_dump(operand)
                if path is None:
                    print(f"no flight_*.json dump found in {operand!r}",
                          file=sys.stderr)
                    return 1
            elif not os.path.exists(path):
                print(f"no such dump file or directory: {operand!r}",
                      file=sys.stderr)
                return 1
            with open(path) as f:
                docs.append(json.load(f))
            print(f"(from {path})", file=sys.stderr)
        print(json.dumps(tracing.fleet_trace(docs), indent=1))
        return 0

    if args.registry:
        from . import metrics
        print(metrics.export_json())
        return 0
    if args.prom:
        from . import export
        # a fresh CLI process shows the import-time instruments, so this
        # doubles as a renderer smoke check (like --registry)
        sys.stdout.write(export.render_prometheus())
        return 0
    if args.compile_report:
        from . import compile_tracker
        print(json.dumps(compile_tracker.compile_report(), indent=1))
        return 0
    if args.xray:
        from . import xray
        # like --registry/--compile-report this reads THIS process's
        # state: drive a serving run first (or read a flight dump's
        # embedded "xray" section) — a fresh CLI process shows an
        # empty ledger, which doubles as an import smoke check
        print(json.dumps(xray.report(), indent=1))
        return 0

    path = args.path
    if path is None:
        directory = args.dir
        if directory is None:
            from .. import flags as _flags
            directory = str(_flags.get_flag("flight_dump_dir")) \
                or "flight_dumps"
        path = find_latest_dump(directory)
        if path is None:
            print(f"no flight_*.json dump found in {directory!r}",
                  file=sys.stderr)
            return 1
    with open(path) as f:
        doc = json.load(f)
    if args.chrome:
        from . import chrome
        print(json.dumps(chrome.trace_from_flight(doc), indent=1))
    else:
        print(json.dumps(doc, indent=1))
    print(f"(from {path})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
