"""Process-wide runtime metrics: Counter / Gauge / Histogram with labels.

The seat of the reference stack's monitoring layer (the host-side stat
helpers feeding `paddle/fluid/platform/profiler` summaries and the
MLPerf-style structured loggers of PAPERS.md): one process-global
registry, instruments created once at import time by the subsystems that
emit them (dispatch, jit, collectives, serving, hapi), read by anyone via
:func:`snapshot` / :func:`export_json`.

Design constraints (ISSUE 1 tentpole):

* **Near-zero cost when disabled.**  ``FLAGS_enable_metrics`` (see
  `paddle_tpu.flags`) flips one module-global boolean; every write path
  (`inc`/`set`/`observe`) checks it first and returns.  Instrument
  objects are module-level constants at their call sites, so the hot
  path is one attribute-free function call.
* **Thread-safe.**  All series mutation happens under one registry lock
  (write paths are host-side bookkeeping — microseconds against op
  dispatch costs of 100s of microseconds).
* **Bounded label cardinality.**  Each metric keeps at most
  ``MAX_SERIES`` distinct label sets; further label combinations
  collapse into a single ``__overflow__`` series instead of growing
  without bound (the standard Prometheus-client guard).

Values are plain Python numbers — never device arrays — so reading
metrics can never force a device sync.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "counter", "gauge", "histogram", "quantile",
    "snapshot", "reset", "export_json",
    "enabled", "set_enabled",
]

# One process-global switch, synced from FLAGS_enable_metrics (flags.py
# installs an on_change hook calling _sync_enabled).  Reads are a plain
# global load — the whole cost of a disabled instrument.
_ENABLED = True


def _sync_enabled(value: bool) -> None:
    global _ENABLED
    _ENABLED = bool(value)


def enabled() -> bool:
    return _ENABLED


def set_enabled(value: bool) -> None:
    """Convenience wrapper over ``paddle_tpu.set_flags``."""
    from .. import flags as _flags
    _flags.set_flags({"enable_metrics": bool(value)})


def _init_from_flag() -> None:
    try:
        from .. import flags as _flags
        _sync_enabled(_flags.get_flag("enable_metrics"))
    except Exception:  # noqa: BLE001 - flag not registered yet (early import)
        pass


_OVERFLOW_KEY = (("__overflow__", "true"),)


class _Metric:
    """Base: named instrument with labeled series."""

    kind = "metric"
    # the op corpus alone is 300+ names; cap well above it so only true
    # cardinality bugs (e.g. a per-request label) hit the overflow series
    MAX_SERIES = 1024

    def __init__(self, name: str, help: str, lock: threading.RLock):  # noqa: A002
        self.name = name
        self.help = help
        self._lock = lock
        self._series: Dict[Tuple[Tuple[str, Any], ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
        if not labels:
            return ()
        key = tuple(sorted(labels.items()))
        if key not in self._series and len(self._series) >= self.MAX_SERIES:
            return _OVERFLOW_KEY
        return key

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    # subclasses: _snapshot_value(raw) -> json-able
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            series = [{"labels": dict(k), "value": self._snapshot_value(v)}
                      for k, v in self._series.items()]
        return {"type": self.kind, "help": self.help, "series": series}

    def _snapshot_value(self, raw):
        return raw


class Counter(_Metric):
    """Monotonically increasing count (ops dispatched, bytes moved...)."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if not _ENABLED:
            return
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0) + n

    def inc_key(self, key: Tuple[Tuple[str, Any], ...], n: float = 1) -> None:
        """Hot-path increment with a PRE-FROZEN label key (a sorted tuple
        of (name, value) pairs, as `_key` would build).  Skips kwargs
        construction and the cardinality guard — only for instruments
        whose label sets are statically bounded (the dispatch hot loop
        caches one key per op name)."""
        if not _ENABLED:
            return
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(tuple(sorted(labels.items())), 0)

    def total(self) -> float:
        """Sum over every label series."""
        with self._lock:
            return sum(self._series.values())


class Gauge(_Metric):
    """Point-in-time value (pool occupancy, tokens/sec of the last tick)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._series[self._key(labels)] = v

    def inc(self, n: float = 1, **labels) -> None:
        if not _ENABLED:
            return
        with self._lock:
            k = self._key(labels)
            self._series[k] = self._series.get(k, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._series.get(tuple(sorted(labels.items())))


class Histogram(_Metric):
    """Distribution of observations (step seconds, compile seconds).

    Fixed cumulative-style buckets chosen for latencies in seconds; each
    series keeps (count, sum, min, max, per-bucket counts) — enough for
    rate/mean/percentile-band readouts without storing observations.
    """

    kind = "histogram"
    DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0, 120.0)

    def __init__(self, name, help, lock, buckets=None):  # noqa: A002
        super().__init__(name, help, lock)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else self.DEFAULT_BUCKETS))

    def observe(self, v: float, **labels) -> None:
        if not _ENABLED:
            return
        with self._lock:
            k = self._key(labels)
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = [0, 0.0, float("inf"), float("-inf"),
                                       [0] * (len(self.buckets) + 1)]
            s[0] += 1
            s[1] += v
            s[2] = min(s[2], v)
            s[3] = max(s[3], v)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s[4][i] += 1
                    break
            else:
                s[4][-1] += 1

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(tuple(sorted(labels.items())))
            return s[0] if s else 0

    def sum(self, **labels) -> float:  # noqa: A003
        with self._lock:
            s = self._series.get(tuple(sorted(labels.items())))
            return s[1] if s else 0.0

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Percentile estimate by linear interpolation inside the bucket
        holding rank ``q`` (ISSUE 6 satellite: one query API across the
        fixed-bucket histograms and the quantile sketches).  Bucket edges
        are clamped to the observed [min, max], which also gives the
        ``+Inf`` bucket a finite upper edge."""
        with self._lock:
            s = self._series.get(tuple(sorted(labels.items())))
            if s is None or not s[0]:
                return None
            count, _, mn, mx, bucket_counts = s
            bucket_counts = list(bucket_counts)
        rank = min(max(float(q), 0.0), 1.0) * count
        cum = 0.0
        for i, c in enumerate(bucket_counts):
            if not c:
                continue
            lo = self.buckets[i - 1] if i > 0 else mn
            hi = self.buckets[i] if i < len(self.buckets) else mx
            lo = min(max(lo, mn), mx)
            hi = min(max(hi, mn), mx)
            if cum + c >= rank:
                return lo + (hi - lo) * ((rank - cum) / c)
            cum += c
        return mx

    def total_count(self) -> int:
        """Observation count over every label series (telemetry diffs
        this across a step bracket)."""
        with self._lock:
            return sum(s[0] for s in self._series.values())

    def total_sum(self) -> float:
        """Sum of observed values over every label series."""
        with self._lock:
            return sum(s[1] for s in self._series.values())

    def _snapshot_value(self, raw):
        count, total, mn, mx, bucket_counts = raw
        return {"count": count, "sum": total,
                "min": mn if count else None,
                "max": mx if count else None,
                "mean": (total / count) if count else None,
                "buckets": {("+inf" if i == len(self.buckets) else
                             repr(self.buckets[i])): c
                            for i, c in enumerate(bucket_counts) if c}}


class Registry:
    """Named instrument store.  ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent, so module-level instruments survive
    re-imports); a name collision across kinds raises."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, **kw):  # noqa: A002
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, help, self._lock, **kw)
            self._metrics[name] = m
        # the metric-description registry (ISSUE 14): the exporter's
        # `# HELP` lines read from one process-wide map, not each
        # instrument — registered outside the registry lock
        if help:
            from . import descriptions as _descriptions
            _descriptions.default(name, help)
        return m

    def counter(self, name: str, help: str = "") -> Counter:  # noqa: A002
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:  # noqa: A002
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",  # noqa: A002
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def quantile(self, name: str, help: str = "",  # noqa: A002
                 alpha: float = 0.01,
                 quantiles: Optional[Sequence[float]] = None):
        """Streaming quantile-sketch instrument (TTFT/TPOT percentiles —
        see :mod:`.quantiles`); rendered as a Prometheus summary."""
        from .quantiles import DEFAULT_QUANTILES, Quantile
        return self._get_or_create(
            Quantile, name, help, alpha=alpha,
            quantiles=tuple(quantiles) if quantiles is not None
            else DEFAULT_QUANTILES)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, Any]:
        """All metrics with at least one recorded series (definitions with
        no data are omitted, so "non-empty snapshot" means data flowed)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.snapshot() for m in metrics if m._series}

    def reset(self) -> None:
        """Clear every series; instrument definitions survive."""
        with self._lock:
            for m in self._metrics.values():
                m.clear()

    def export_json(self, path: Optional[str] = None, indent: int = 2) -> str:
        doc = {"schema": "paddle_tpu.metrics/v1",
               "unix_time": time.time(),
               "metrics": self.snapshot()}
        text = json.dumps(doc, indent=indent, sort_keys=True, default=str)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text


# ------------------------------------------------------------ default registry
_default = Registry()


def counter(name: str, help: str = "") -> Counter:  # noqa: A002
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:  # noqa: A002
    return _default.gauge(name, help)


def histogram(name: str, help: str = "",  # noqa: A002
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default.histogram(name, help, buckets)


def quantile(name: str, help: str = "", alpha: float = 0.01,  # noqa: A002
             quantiles: Optional[Sequence[float]] = None):
    return _default.quantile(name, help, alpha, quantiles)


def get(name: str) -> Optional[_Metric]:
    return _default.get(name)


def snapshot() -> Dict[str, Any]:
    return _default.snapshot()


def reset() -> None:
    _default.reset()


def export_json(path: Optional[str] = None, indent: int = 2) -> str:
    return _default.export_json(path, indent)


_init_from_flag()
