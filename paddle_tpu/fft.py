"""paddle.fft namespace — populated from the YAML single source.

Parity: `python/paddle/fft.py`.  Which ops land here is decided by the
`namespace: fft` field in `ops/specs/ops.yaml`; adding an op there and
regenerating is all it takes.
"""

from .ops import generated_ops as _g

__all__ = sorted(n for n, ns in _g._NAMESPACES.items() if ns == "fft")

for _name in __all__:
    globals()[_name] = getattr(_g, _name)
del _name, _g
