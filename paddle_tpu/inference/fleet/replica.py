"""Engine replicas + fleet orchestration (ISSUE 16 tentpole c).

:class:`Replica` is one serving engine behind its OWN loopback HTTP
frontend (the per-server engine binding in observability/http.py —
the process-global ``attach_engine`` can only name one engine, a fleet
needs one front door per replica).  The frontend port is allocated once
and survives engine restarts: ``restart()`` swaps a fresh engine behind
the same socket, so the router's address book never goes stale.

:class:`Fleet` owns N replicas plus the router and runs the
operational drill this PR exists for — **zero-downtime rolling
restart**:

    for each replica:  cordon -> drain (in-flight requests finish,
    prefix KV exports) -> engine thread exits -> fresh engine
    constructs (imports the export bundle, warm) -> ready -> uncordon

The router reroutes the cordoned replica's share to the rest of the
fleet (rendezvous order: only that share moves) and routes it back
after uncordon; requests already streaming on the draining engine
finish during the drain window.  The chaos-tested gate in
tests/test_fleet.py asserts zero dropped requests through a full
rolling restart under concurrent traffic, and the ``fleet`` bench rung
reports goodput-during-restart against steady-state.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ...observability import flight_recorder as _flight
from ...observability import http as _http

__all__ = ["Replica", "Fleet"]


class Replica:
    """One engine + its loopback frontend.  ``engine_factory()`` builds
    a fresh ServingEngine each (re)start — close over
    ``prefix_export_dir`` so successive engines drain-export to and
    warm-import from the replica's own bundle root.

    CONCURRENT replicas must not share one model object: engine traces
    bind parameter values into the model's Parameters (engine-local
    state on a shared object), so two engines tracing at once leak
    tracers into each other's programs.  Give each replica's factory
    its own model instance — same weights, own copy, exactly like a
    multi-process fleet."""

    def __init__(self, name: str, engine_factory: Callable[[], object]):
        self.name = name
        self._factory = engine_factory
        self.engine = None
        self.server: Optional[_http.MetricsServer] = None
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self.restarts = 0
        # Replica-local flight recorder: in a real fleet each process has
        # its own default recorder; in-process replicas need one per
        # engine so ``dump --fleet-trace`` sees per-replica timelines
        # instead of one interleaved mess.  Survives restarts — the
        # recorder is the replica's history, not the engine's.
        self.flight = _flight.FlightRecorder()
        self.flight.record_event("replica_meta", replica=name)

    @property
    def addr(self) -> str:
        if self.server is None:
            raise RuntimeError(f"replica {self.name} never started")
        return f"127.0.0.1:{self.server.port}"

    def start(self, wait_ready_s: float = 120.0) -> None:
        """Construct the engine (warm-imports its export bundle when one
        exists), bind it behind the replica's frontend, and run
        ``serve_forever`` on a daemon thread until ready."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"replica {self.name} already running")
        self.engine = self._factory()
        self.engine._flight_rec = self.flight
        if self.server is None:
            self.server = _http.MetricsServer(0, "127.0.0.1",
                                              engine=self.engine)
        else:
            self.server.bind_engine(self.engine)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.engine.serve_forever, args=(self._stop,),
            name=f"fleet-{self.name}", daemon=True)
        self._thread.start()
        deadline = time.monotonic() + wait_ready_s
        while not self.engine._ready:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {self.name} not ready in {wait_ready_s}s")
            if not self._thread.is_alive():
                raise RuntimeError(
                    f"replica {self.name} engine loop died during start")
            time.sleep(0.01)

    def request_drain(self) -> None:
        if self.engine is not None:
            self.engine.request_drain()

    def drain_and_stop(self, timeout_s: float = 120.0) -> dict:
        """Graceful stop: ask the engine loop to drain (in-flight work
        finishes, waiting queue cancels ``outcome=drained``, prefix KV
        exports) and join the loop thread.  Returns the drain report."""
        if self._thread is None:
            return {}
        self.request_drain()
        self._thread.join(timeout=timeout_s)
        if self._thread.is_alive():
            # loop wedged: hard-stop (crash-only — the export bundle,
            # if any, is still the warm-restart source of truth)
            self._stop.set()
            self._thread.join(timeout=5.0)
        self._thread = None
        return dict(self.engine._drain_info or {})

    def restart(self, wait_ready_s: float = 120.0) -> dict:
        """drain -> export -> fresh engine -> import -> ready, behind
        the SAME frontend port.  Returns {"drain": ..., "import": ...,
        "restart_s": ...}."""
        t0 = time.monotonic()
        drain = self.drain_and_stop()
        self.start(wait_ready_s=wait_ready_s)
        self.restarts += 1
        return {"drain": drain,
                "import": dict(self.engine._prefix_import_info or {}),
                "restart_s": round(time.monotonic() - t0, 3)}

    def dump_flight(self, path: str) -> str:
        """Write this replica's flight snapshot (steps + events, incl.
        its span records) as JSON to ``path`` for ``dump --fleet-trace``
        merging.  Returns the path."""
        import json

        with open(path, "w") as f:
            json.dump(self.flight.snapshot(reason="fleet_trace"), f)
        return path

    def stop(self) -> None:
        """Hard stop: kill the loop and close the frontend socket."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.server is not None:
            self.server.close()
            self.server = None


class Fleet:
    """N replicas + the router, with the rolling-restart drill."""

    def __init__(self, replicas: List[Replica], router) -> None:
        self.replicas = replicas
        self.router = router

    @classmethod
    def build(cls, engine_factory: Callable[[str], object], n: int,
              export_root: str, wait_ready_s: float = 120.0,
              **router_kw) -> "Fleet":
        """Start ``n`` replicas (``engine_factory(prefix_export_dir)``
        builds each engine; replica i exports under
        ``<export_root>/<name>``) and a router over them."""
        import os

        from .router import FleetRouter
        replicas = []
        for i in range(n):
            name = f"r{i}"
            root = os.path.join(export_root, name)
            rep = Replica(name,
                          lambda root=root: engine_factory(root))
            rep.start(wait_ready_s=wait_ready_s)
            replicas.append(rep)
        router_kw.setdefault("flight_recorder",
                             _flight.FlightRecorder())
        router = FleetRouter({r.name: r.addr for r in replicas},
                             **router_kw)
        return cls(replicas, router)

    def rolling_restart(self, wait_ready_s: float = 120.0,
                        quiesce_s: float = 30.0) -> dict:
        """Restart every replica, one at a time, behind the router:
        cordon first (no new routes can race the healthz flip), wait for
        the replica's WAITING queue to empty (requests routed in the
        cordon race window admit and run instead of being
        drain-cancelled), then drain/export/restart/import, then
        uncordon + re-poll.  Anything that still slips into the drain
        window gets the replica's 503-draining answer and fails over at
        the router — the two halves of the zero-dropped-requests gate.
        The fleet keeps serving throughout — that is the whole point."""
        reports: Dict[str, dict] = {}
        t0 = time.monotonic()
        for rep in self.replicas:
            self.router.cordon(rep.name)
            try:
                self._wait_quiesced(rep, quiesce_s)
                reports[rep.name] = rep.restart(wait_ready_s=wait_ready_s)
            finally:
                self.router.uncordon(rep.name)
            self.router.poll_once(rep.name)
        return {"replicas": reports,
                "rolling_restart_s": round(time.monotonic() - t0, 3)}

    @staticmethod
    def _wait_quiesced(rep: Replica, timeout_s: float) -> None:
        """Wait (bounded) until nothing is queued on ``rep``: cordoned
        replicas stop RECEIVING traffic but requests already past the
        router's routing decision may still land for a moment; once
        ``waiting`` is empty every remaining request holds a slot and
        the drain lets it finish."""
        deadline = time.monotonic() + timeout_s
        eng = rep.engine
        while time.monotonic() < deadline:
            if eng is None or (not eng.waiting and not eng.prefilling):
                return
            time.sleep(0.02)

    def dump_flight(self, root: str) -> List[str]:
        """Write one flight dump per fleet process under ``root`` —
        router first (the ``dump --fleet-trace`` operand order puts the
        timebase owner at pid 1), then each replica.  Returns the paths,
        in that order."""
        import json
        import os

        os.makedirs(root, exist_ok=True)
        paths = [os.path.join(root, "flight_router.json")]
        with open(paths[0], "w") as f:
            json.dump(self.router._flightrec().snapshot(
                reason="fleet_trace"), f)
        for rep in self.replicas:
            paths.append(rep.dump_flight(
                os.path.join(root, f"flight_{rep.name}.json")))
        return paths

    def close(self) -> None:
        self.router.close()
        for rep in self.replicas:
            rep.stop()
