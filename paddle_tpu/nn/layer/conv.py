"""Conv layers. Parity: `python/paddle/nn/layer/conv.py`.
Weight layout [out_c, in_c/groups, *k] (transpose: [in_c, out_c/groups, *k])."""

from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose"]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else v * n))
    return tuple(int(v) for _ in range(n))


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, padding_mode, weight_attr, bias_attr,
                 data_format, dims, transposed=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tuplize(kernel_size, dims)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._dims = dims
        self._transposed = transposed
        self._output_padding = output_padding
        if transposed:
            shape = [in_channels, out_channels // groups] + list(self._kernel_size)
        else:
            shape = [out_channels, in_channels // groups] + list(self._kernel_size)
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound)) \
            if bias_attr is not False else None

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 1, True, output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 2, True, output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 3, True, output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
