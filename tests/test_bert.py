"""BERT family: MLM training, masking semantics, jit capture, TP parity.

Mirrors the reference's BERT rung (BASELINE config 3) test strategy.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.bert import (BertForMaskedLM,
                                    BertForSequenceClassification,
                                    BertModel, bert_tiny)


def ids_batch(B=2, S=16, vocab=100, seed=0):
    return paddle.to_tensor(np.random.RandomState(seed)
                            .randint(4, vocab, (B, S)).astype(np.int32))


def test_bert_shapes_and_pooler():
    paddle.seed(0)
    m = BertModel(bert_tiny())
    seq, pooled = m(ids_batch(), token_type_ids=paddle.to_tensor(
        np.zeros((2, 16), np.int32)))
    assert tuple(seq.shape) == (2, 16, 64)
    assert tuple(pooled.shape) == (2, 64)
    assert float(np.abs(np.asarray(pooled._value)).max()) <= 1.0  # tanh


def test_attention_mask_excludes_padding():
    """Masked (pad) positions must not influence other tokens' outputs."""
    paddle.seed(0)
    m = BertModel(bert_tiny(dropout=0.0))
    m.eval()
    ids = np.random.RandomState(1).randint(4, 100, (3, 8)).astype(np.int32)
    mask = np.array([[1, 1, 1, 1, 1, 1, 0, 0],
                     [1, 1, 1, 1, 0, 0, 0, 0],
                     [1, 1, 1, 1, 1, 1, 1, 1]], np.int32)
    seq1, _ = m(paddle.to_tensor(ids), attention_mask=paddle.to_tensor(mask))
    ids2 = ids.copy()
    ids2[0, 6:] = 99  # change only each row's padded tail
    ids2[1, 4:] = 99
    seq2, _ = m(paddle.to_tensor(ids2),
                attention_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(np.asarray(seq1._value)[0, :6],
                               np.asarray(seq2._value)[0, :6],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(seq1._value)[1, :4],
                               np.asarray(seq2._value)[1, :4],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(seq1._value)[2],
                               np.asarray(seq2._value)[2],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow   # tier-1 budget (ISSUE 9): heavy, not on the serving/training core path
def test_mlm_learns_identity_with_masking():
    """15%-style masking: model must learn to reconstruct masked tokens."""
    paddle.seed(0)
    cfg = bert_tiny(vocab_size=64, dropout=0.0)
    m = BertForMaskedLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=2e-3,
                                 parameters=m.parameters())
    rng = np.random.RandomState(0)
    base = rng.randint(4, 60, (8, 16)).astype(np.int32)
    MASK = 3
    from paddle_tpu.jit import to_static

    def train_step(x, y):
        loss = m.compute_loss(x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = to_static(train_step)
    losses = []
    for i in range(60):
        mask_pos = rng.rand(*base.shape) < 0.3
        x = np.where(mask_pos, MASK, base).astype(np.int32)
        y = np.where(mask_pos, base, -100).astype(np.int32)  # only masked
        loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
        losses.append(float(loss._value))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_sequence_classification_head():
    paddle.seed(0)
    m = BertForSequenceClassification(bert_tiny(), num_classes=3)
    logits = m(ids_batch())
    assert tuple(logits.shape) == (2, 3)
    loss = paddle.nn.functional.cross_entropy(
        logits, paddle.to_tensor(np.array([0, 2], np.int32)))
    loss.backward()
    assert m.classifier.weight.grad is not None


def test_bert_tensor_parallel_parity(hybrid_mesh):
    """mp=2 TP encoder must match the serial encoder's outputs."""
    paddle.seed(7)
    cfg = bert_tiny(dropout=0.0)
    serial = BertForMaskedLM(cfg)
    serial.eval()
    ids = ids_batch(seed=3)
    want = np.asarray(serial(ids)._value)

    paddle.seed(7)  # identical init order -> identical weights
    cfg_tp = bert_tiny(dropout=0.0, tensor_parallel=True)
    tp = BertForMaskedLM(cfg_tp)
    tp.eval()
    got = np.asarray(tp(ids)._value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-5)
