"""Norm layers. Parity: `python/paddle/nn/layer/norm.py`."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "RMSNorm", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN.  Under pjit/DataParallel the batch axis is sharded and
    XLA computes global batch stats automatically when the reduction spans the
    mesh axis; eager multi-process sync is handled by the DataParallel wrapper.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer,
                                                                SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                out.weight._value = layer.weight._value
            if layer.bias is not None:
                out.bias._value = layer.bias._value
            out._mean._value = layer._mean._value
            out._variance._value = layer._variance._value
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(self._normalized_shape,
                                          attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm: planned")
