"""Scrape + serve endpoint: a stdlib HTTP daemon serving /metrics,
/healthz, /requests and (ISSUE 11) a streaming ``POST /generate``.

ISSUE 6 tentpole (c): the answer to "what is p99 TTFT right now?" from
OUTSIDE the process.  One ``http.server.ThreadingHTTPServer`` on a
daemon thread — no third-party dependency, nothing on the hot path (the
handler reads the registry under its locks exactly like ``snapshot()``).

Endpoints:

* ``GET /metrics``  — the registry in Prometheus text exposition format
  (:func:`.export.render_prometheus`), content type
  ``text/plain; version=0.0.4``.
* ``GET /healthz``  — liveness JSON (``{"ok": true, ...}``); a scraper
  or load balancer can distinguish "process up" from "port dead".
* ``GET /requests`` — the last-K per-request serving trace records as a
  JSON array (``?n=`` caps K, default 64).
* ``POST /generate`` — the minimal streaming serve frontend (ISSUE 11):
  a JSON body (``prompt_ids`` + the `Request` sampling knobs +
  ``timeout_s``) enqueues a request into the :func:`attach_engine`'d
  serving engine and answers a Server-Sent Events token stream —
  ``data: {"token": id}`` per emitted token, a terminal ``event: done``
  with the full output for finished/cancelled requests, and (ISSUE 15)
  a terminal ``event: error`` frame ``data: {"rid", "reason",
  "output_ids"}`` when the request ends
  ``outcome=error|poisoned|slo_shed|drained`` — a stream never just
  closes silently.  The handler thread never touches device state: it
  enqueues, then drains the request's token queue fed by the engine
  loop's harvests.  A client disconnect (the keepalive ping write
  fails) or ``timeout_s`` expiry calls ``Request.cancel()``, which the
  engine's next scheduler boundary turns into slot eviction + block
  release.
* ``POST /drain`` — graceful-drain trigger (ISSUE 15): flips the
  attached engine's drain request flag (the `serve_forever` loop picks
  it up at its next boundary: admission closes, /healthz answers 503
  ``{"reason": "draining"}``, in-flight requests finish up to
  ``FLAGS_serving_drain_timeout_s``, the waiting queue is cancelled
  with SSE error frames, and the prefix cache exports).  Answers 202
  immediately — the drain itself runs on the engine loop thread.

Security: binds ``FLAGS_metrics_host`` (default ``127.0.0.1`` — the
endpoint exposes operational data, so exposure beyond the host must be
an explicit operator decision).  ``FLAGS_metrics_port`` (default 0 =
disabled) gates auto-start: :func:`start_from_flags` is called by
``ServingEngine.run()`` and ``Model.fit()`` and is a no-op unless the
flag is set.  ``FLAGS_serving_http_port`` (default 0 = disabled)
auto-starts the serve endpoint on 127.0.0.1 ONLY — the generate route
accepts work, so it never widens beyond loopback via flags.  Calling
:func:`serve` directly with ``port=0`` binds an ephemeral port (tests).
"""

from __future__ import annotations

import json
import queue as _queue
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import export as _export
from . import metrics as _metrics

__all__ = ["MetricsServer", "serve", "start_from_flags", "stop",
           "current", "attach_engine", "current_engine",
           "start_serving_from_flags", "serving_server"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_metrics/1.0"

    def _engine(self):
        """The serving engine THIS server fronts: the per-server binding
        (``MetricsServer(engine=...)`` — one frontend per replica in an
        in-process fleet) wins over the process-global
        :func:`attach_engine` registration."""
        ref = getattr(self.server, "_engine_ref", None)
        eng = ref() if ref is not None else None
        return eng if eng is not None else current_engine()

    def _send(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            if url.path == "/metrics":
                body = _export.render_prometheus().encode()
                self._send(200,
                           "text/plain; version=0.0.4; charset=utf-8",
                           body)
            elif url.path == "/metrics/snapshot":
                # mergeable registry state + engine telemetry for the
                # fleet federation poll (ISSUE 17): counters/gauges as
                # numbers, quantile instruments as DDSketch bucket
                # states the router merges by bucket addition
                from . import federation as _federation
                doc = _federation.local_snapshot(engine=self._engine())
                self._send(200, "application/json",
                           json.dumps(doc, default=repr).encode())
            elif url.path == "/healthz":
                import os
                doc = {"ok": True, "pid": os.getpid(),
                       "unix_time": round(time.time(), 3),
                       "metrics_enabled": _metrics.enabled()}
                # readiness (ISSUE 14 satellite): with a serving engine
                # attached this is a real readiness probe — 503 with
                # {"ready": false, "reason": "warmup"} until warmup
                # completed and admission opened, then the engine's
                # warmup/queue-depth/uptime evidence.  With no engine
                # (training, metrics-only) it stays the liveness check.
                eng = self._engine()
                if eng is not None:
                    try:
                        doc.update(eng.health())
                    except Exception:  # noqa: BLE001 - probe must answer
                        pass
                code = 503 if doc.get("ready") is False else 200
                self._send(code, "application/json",
                           json.dumps(doc).encode())
            elif url.path == "/requests":
                try:
                    n = int(parse_qs(url.query).get("n", ["64"])[0])
                except (ValueError, IndexError):
                    n = 64
                body = json.dumps(_export.recent_requests(n),
                                  default=repr).encode()
                self._send(200, "application/json", body)
            else:
                self._send(404, "text/plain; charset=utf-8",
                           b"not found; endpoints: /metrics /healthz "
                           b"/requests\n")
        except BrokenPipeError:  # scraper hung up mid-response
            pass

    # ------------------------------------------ POST /generate (SSE)
    def do_POST(self) -> None:  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            if url.path == "/generate":
                self._generate()
            elif url.path == "/drain":
                self._drain()
            else:
                self._send(404, "text/plain; charset=utf-8",
                           b"not found; POST endpoints: /generate "
                           b"/drain\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up; _generate already propagated cancel

    def _drain(self) -> None:
        eng = self._engine()
        if eng is None:
            self._send(503, "application/json",
                       b'{"error": "no serving engine attached"}')
            return
        eng.request_drain()
        self._send(202, "application/json", json.dumps(
            {"draining": True,
             "running": eng.B - len(eng.free_slots),
             "waiting": len(eng.waiting)}).encode())

    def _sse(self, payload: dict, event: Optional[str] = None) -> None:
        head = f"event: {event}\n" if event else ""
        self.wfile.write(
            (head + "data: " + json.dumps(payload) + "\n\n").encode())
        self.wfile.flush()

    def _generate(self) -> None:
        eng = self._engine()
        if eng is None:
            self._send(503, "application/json",
                       b'{"error": "no serving engine attached"}')
            return
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt_ids = [int(t) for t in body["prompt_ids"]]
        except (KeyError, TypeError, ValueError) as e:
            self._send(400, "application/json", json.dumps(
                {"error": f"bad request body: {e!r}"}).encode())
            return
        from ..inference.serving import Request
        from . import tracing as _tracing
        # distributed trace context (ISSUE 17): the fleet router (or
        # any client) ships `X-Graft-Trace: <trace_id>-<span_id>`; the
        # id threads into the Request so every lifecycle/flight record
        # this replica writes joins the cross-process trace
        trace_id, parent_span = _tracing.parse_header(
            self.headers.get(_tracing.TRACE_HEADER))
        req = Request(
            prompt_ids,
            max_new_tokens=int(body.get("max_new_tokens", 32)),
            eos_token_id=body.get("eos_token_id"),
            do_sample=bool(body.get("do_sample", False)),
            temperature=float(body.get("temperature", 1.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=body.get("seed"),
            priority=int(body.get("priority", 0)),
            trace_id=trace_id, parent_span=parent_span)
        timeout_s = float(body.get("timeout_s", 120.0))
        # the stream queue must exist BEFORE enqueue: the engine thread
        # may emit the first token between add_request and our loop
        req._stream_q = _queue.Queue()
        try:
            eng.add_request(req)
        except ValueError as e:
            if eng._draining or eng._drain_requested:
                # NOT the client's fault: this replica is going away.
                # 503 (not 400) so a fleet router fails the request over
                # to the next replica instead of relaying a dead end —
                # the zero-dropped-requests half of a rolling restart.
                self._send(503, "application/json", json.dumps(
                    {"error": str(e), "reason": "draining",
                     "rid": req.rid}).encode())
                return
            # over_context / capacity rejection: authoritative
            self._send(400, "application/json", json.dumps(
                {"error": str(e), "rid": req.rid}).encode())
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        deadline = time.monotonic() + timeout_s
        i = 0
        try:
            while True:
                try:
                    tok = req._stream_q.get(timeout=0.05)
                except _queue.Empty:
                    if time.monotonic() > deadline:
                        req.cancel()
                        self._sse({"error": "timeout", "rid": req.rid,
                                   "output_ids": list(req.output_ids)},
                                  event="error")
                        return
                    # keepalive comment: also our disconnect probe — a
                    # gone client raises here and the except below
                    # propagates the cancel to the engine
                    self.wfile.write(b": ping\n\n")
                    self.wfile.flush()
                    continue
                if tok is None:         # terminal sentinel
                    outcome = req.outcome or (
                        "finished" if req.done else
                        "slo_shed" if req.shed else "cancelled")
                    if outcome in ("error", "poisoned", "slo_shed",
                                   "drained"):
                        # the engine ended the stream, not the client:
                        # a terminal error frame names WHY instead of
                        # silently closing (ISSUE 15 contract — format
                        # pinned in test_continuous_batching)
                        self._sse({"rid": req.rid, "reason": outcome,
                                   "output_ids": list(req.output_ids)},
                                  event="error")
                    else:
                        self._sse({"rid": req.rid, "outcome": outcome,
                                   "output_ids": list(req.output_ids)},
                                  event="done")
                    return
                self._sse({"token": int(tok), "n": i})
                i += 1
        except (BrokenPipeError, ConnectionResetError):
            req.cancel()            # client went away mid-stream

    def log_message(self, format, *args):  # noqa: A002 - http.server API
        pass  # scrapes every few seconds must not spam stderr


class MetricsServer:
    """One running scrape endpoint; ``port`` is the BOUND port (useful
    when constructed with port 0).  ``engine`` binds a specific serving
    engine to THIS server's /generate, /drain and /healthz routes
    (weakly, like :func:`attach_engine`) — the per-replica frontend an
    in-process fleet needs, where the process-global attachment can
    only name one engine."""

    def __init__(self, port: int, host: str = "127.0.0.1", engine=None):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd._engine_ref = (
            weakref.ref(engine) if engine is not None else None)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-tpu-metrics",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def bind_engine(self, engine) -> None:
        """Swap the engine behind this server's routes.  A fleet replica
        keeps ONE frontend for its whole life — the port is the router's
        stable address — while restarts replace the engine behind it."""
        self._httpd._engine_ref = (
            weakref.ref(engine) if engine is not None else None)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_lock = threading.Lock()
_server: Optional[MetricsServer] = None


def serve(port: int, host: str = "127.0.0.1") -> MetricsServer:
    """Start (or return) the process's scrape endpoint.  Idempotent: a
    second call returns the running server regardless of arguments."""
    global _server
    with _lock:
        if _server is None:
            _server = MetricsServer(port, host)
        return _server


def start_from_flags() -> Optional[MetricsServer]:
    """Auto-start hook for the long-running entry points
    (``ServingEngine.run``, ``Model.fit``): starts the endpoint when
    ``FLAGS_metrics_port`` > 0, else a no-op.  Never raises — a busy
    port must not take down training/serving."""
    if _server is not None:
        return _server
    try:
        from .. import flags as _flags
        port = int(_flags.get_flag("metrics_port"))
        if port <= 0:
            return None
        host = str(_flags.get_flag("metrics_host"))
        return serve(port, host)
    except Exception:  # noqa: BLE001 - observability must not kill the job
        return None


def current() -> Optional[MetricsServer]:
    return _server


# ---------------------------------------------------------------------------
# Streaming serve endpoint (ISSUE 11): POST /generate needs an engine.
# The engine is attached as a WEAK reference — a registered engine must
# not outlive its owner just because a server thread exists.
_engine_ref = None
_serving_server: Optional[MetricsServer] = None


def attach_engine(engine) -> None:
    """Register the serving engine POST /generate enqueues into (and
    /healthz reads readiness from).  Called by ``ServingEngine.run()``/
    ``serve_forever()``; the LAST attached engine wins (one process,
    one front door).  ``attach_engine(None)`` detaches (tests)."""
    global _engine_ref
    _engine_ref = weakref.ref(engine) if engine is not None else None


def current_engine():
    ref = _engine_ref
    return ref() if ref is not None else None


def start_serving_from_flags() -> Optional[MetricsServer]:
    """Auto-start the streaming serve endpoint when
    ``FLAGS_serving_http_port`` > 0 (loopback only — the route accepts
    work).  Idempotent; never raises: a busy port must not take down
    the engine loop."""
    global _serving_server
    if _serving_server is not None:
        return _serving_server
    try:
        from .. import flags as _flags
        port = int(_flags.get_flag("serving_http_port"))
        if port <= 0:
            return None
        with _lock:
            if _serving_server is None:
                _serving_server = MetricsServer(port, "127.0.0.1")
            return _serving_server
    except Exception:  # noqa: BLE001 - frontend must not kill serving
        return None


def serving_server() -> Optional[MetricsServer]:
    return _serving_server


def stop() -> None:
    global _server, _serving_server
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
        if _serving_server is not None:
            _serving_server.close()
            _serving_server = None
