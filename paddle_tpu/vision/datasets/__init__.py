"""Vision datasets. Parity: `python/paddle/vision/datasets/`.

No-network environment: MNIST/Cifar load from a local path when present
(`image_path`/`data_file`), else fall back to a deterministic synthetic set of
the same shapes — tests and benchmarks use the synthetic path.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers", "VOC2012"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = synthetic_size or (6000 if mode == "train" else 1000)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so models can actually learn
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, lbl in enumerate(self.labels):
                img = rng.rand(28, 28) * 64
                r, c = divmod(int(lbl), 4)
                img[r * 7:(r + 1) * 7 + 7, c * 7:(c + 1) * 7] += 180
                self.images[i] = np.clip(img, 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img[None]  # CHW
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        n = synthetic_size or (5000 if mode == "train" else 1000)
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = (rng.rand(n, 32, 32, 3) * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    """100-class CIFAR; synthetic fallback mirrors the label space."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        super().__init__(data_file, mode, transform, download, backend,
                         synthetic_size)
        rng = np.random.RandomState(2 if mode == "train" else 3)
        self.labels = rng.randint(0, 100, len(self.labels)).astype(np.int64)


def _scan_files(root, extensions, is_valid_file):
    """Sorted walk of image files under root (shared by the folder
    datasets; one place for extension/validity policy)."""
    extensions = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
    if is_valid_file is None:
        is_valid_file = lambda p: p.lower().endswith(extensions)  # noqa: E731
    found = []
    for base, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            path = os.path.join(base, fname)
            if is_valid_file(path):
                found.append(path)
    return found


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


class DatasetFolder(Dataset):
    """Parity: `python/paddle/vision/datasets/folder.py` DatasetFolder —
    samples arranged as root/class_x/xxx.ext; classes discovered from the
    subdirectory names in sorted order."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _scan_files(os.path.join(root, c), extensions,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Parity: folder.py ImageFolder — a FLAT (unlabelled) image list:
    every image under root, no class structure, returns [img]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _pil_loader
        self.transform = transform
        self.samples = _scan_files(root, extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Parity: `python/paddle/vision/datasets/flowers.py` (102-category
    Oxford flowers).  Local-file mode reads the official scipy-format
    label .mat + image tgz when given; the no-network fallback is a
    deterministic synthetic set with the same shapes/label space."""

    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.transform = transform
        if mode not in ("train", "valid", "test"):
            raise ValueError(
                f"Flowers mode must be train/valid/test, got {mode!r}")
        if data_file and os.path.exists(data_file):
            if not (label_file and setid_file):
                raise ValueError(
                    "Flowers with data_file also needs label_file "
                    "(imagelabels.mat) and setid_file (setid.mat)")
            self._init_from_files(data_file, label_file, setid_file, mode)
            return
        n = synthetic_size or (1020 if mode == "train" else 102)
        rng = np.random.RandomState({"train": 10, "valid": 11,
                                     "test": 12}.get(mode, 10))
        self.labels = (np.arange(n) % self.NUM_CLASSES).astype(np.int64)
        self.images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)

    def _init_from_files(self, data_file, label_file, setid_file, mode):
        import tarfile

        from scipy.io import loadmat
        labels = loadmat(label_file)["labels"][0] - 1
        split_key = {"train": "trnid", "valid": "valid",
                     "test": "tstid"}[mode]
        ids = loadmat(setid_file)[split_key][0]
        self._tar_path = data_file
        self._tar = None     # opened lazily PER PROCESS: an open TarFile
        with tarfile.open(data_file) as tf:   # can't pickle into workers
            self._names = {int(m.name.split("_")[-1].split(".")[0]): m.name
                           for m in tf.getmembers()
                           if m.name.endswith(".jpg")}
        self._ids = [int(i) for i in ids]
        self.labels = np.asarray([labels[i - 1] for i in self._ids],
                                 np.int64)
        self.images = None

    def __getitem__(self, idx):
        if self.images is not None:
            img = self.images[idx]
        else:
            import tarfile

            from PIL import Image
            if self._tar is None:
                self._tar = tarfile.open(self._tar_path)
            f = self._tar.extractfile(self._names[self._ids[idx]])
            img = np.asarray(Image.open(f).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """Parity: `python/paddle/vision/datasets/voc2012.py` (segmentation:
    image + per-pixel class mask).  Local-path mode walks a VOCdevkit
    tree (JPEGImages/ + SegmentationClass/ + ImageSets/Segmentation
    split lists); fallback is synthetic image/mask pairs with VOC's 21
    labels (20 classes + background) and 255 ignore borders."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        if mode == "val":
            mode = "valid"       # torchvision spelling, accepted
        if mode not in ("train", "valid", "test"):
            raise ValueError(
                f"VOC2012 mode must be train/valid/test, got {mode!r}")
        if data_file and os.path.isdir(data_file):
            split = {"train": "train", "valid": "val",
                     "test": "val"}[mode]
            lst = os.path.join(data_file, "ImageSets", "Segmentation",
                               split + ".txt")
            with open(lst) as f:
                names = [ln.strip() for ln in f if ln.strip()]
            self._pairs = [
                (os.path.join(data_file, "JPEGImages", n + ".jpg"),
                 os.path.join(data_file, "SegmentationClass", n + ".png"))
                for n in names]
            self.images = None
            return
        n = synthetic_size or (120 if mode == "train" else 30)
        rng = np.random.RandomState(20 if mode == "train" else 21)
        self.images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
        self.masks = rng.randint(0, self.NUM_CLASSES,
                                 (n, 64, 64)).astype(np.uint8)
        self.masks[:, 0, :] = 255          # VOC ignore-border label
        self._pairs = None

    def __getitem__(self, idx):
        if self.images is not None:
            img, mask = self.images[idx], self.masks[idx]
        else:
            from PIL import Image
            ip, mp = self._pairs[idx]
            img = np.asarray(Image.open(ip).convert("RGB"))
            mask = np.asarray(Image.open(mp))
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, mask.astype(np.int64)

    def __len__(self):
        return len(self._pairs) if self._pairs is not None \
            else len(self.images)
