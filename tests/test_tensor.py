"""Tensor basics: creation, meta, conversion, indexing, in-place."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert str(t.dtype) == "float32"
    assert t.stop_gradient is True
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_inference():
    assert str(paddle.to_tensor([1, 2]).dtype) == "int32"
    assert str(paddle.to_tensor([1.5]).dtype) == "float32"
    assert str(paddle.to_tensor([True]).dtype) == "bool"
    # TPU-native policy: 64-bit requests canonicalize to 32-bit in x32 mode
    assert str(paddle.to_tensor([1], dtype="float64").dtype) == "float32"
    assert str(paddle.to_tensor(np.zeros((2,), np.float16)).dtype) == "float16"


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).sum().item() == 4.0
    assert paddle.full([2], 7).numpy().tolist() == [7.0, 7.0]
    assert paddle.arange(10).shape == [10]
    assert paddle.eye(3).numpy()[1, 1] == 1.0
    assert paddle.linspace(0, 1, 5).shape == [5]
    z = paddle.zeros_like(paddle.ones([3, 3]))
    assert z.sum().item() == 0.0


def test_item_tolist():
    t = paddle.to_tensor([5.0])
    assert t.item() == 5.0
    assert paddle.to_tensor([[1, 2]]).tolist() == [[1, 2]]


def test_astype_cast():
    t = paddle.ones([2], dtype="float32")
    assert str(t.astype("int32").dtype) == "int32"
    assert str(paddle.cast(t, "bool").dtype) == "bool"


def test_indexing_read():
    t = paddle.to_tensor(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert t[0].shape == [3, 4]
    assert t[0, 1, 2].item() == 6.0
    assert t[:, 1].shape == [2, 4]
    assert t[..., -1].shape == [2, 3]
    assert t[0, ::2].shape == [2, 4]
    idx = paddle.to_tensor([0, 2])
    assert t[0, idx].shape == [2, 4]


def test_indexing_write():
    t = paddle.zeros([3, 3])
    t[1, 1] = 9.0
    assert t.numpy()[1, 1] == 9.0
    t[0] = paddle.ones([3])
    assert t.numpy()[0].tolist() == [1, 1, 1]


def test_bool_mask_select():
    t = paddle.to_tensor([1.0, -2.0, 3.0])
    out = t[t > 0]
    assert out.numpy().tolist() == [1.0, 3.0]


def test_inplace_helpers():
    t = paddle.ones([2, 2])
    t.add_(paddle.ones([2, 2]))
    assert t.numpy()[0, 0] == 2.0
    t.zero_()
    assert t.sum().item() == 0.0
    t.fill_(3.0)
    assert t.numpy()[1, 1] == 3.0


def test_operators():
    a = paddle.to_tensor([2.0, 4.0])
    b = paddle.to_tensor([1.0, 2.0])
    assert (a + b).numpy().tolist() == [3, 6]
    assert (a - b).numpy().tolist() == [1, 2]
    assert (a * b).numpy().tolist() == [2, 8]
    assert (a / b).numpy().tolist() == [2, 2]
    assert (a ** 2).numpy().tolist() == [4, 16]
    assert (-a).numpy().tolist() == [-2, -4]
    assert (a @ b.reshape([2, 1])).shape == [1]
    assert (a > b).numpy().tolist() == [True, True]
    assert (1.0 + a).numpy().tolist() == [3, 5]
    assert (8.0 / a).numpy().tolist() == [4, 2]


def test_detach_and_clone():
    a = paddle.to_tensor([1.0], stop_gradient=False)
    d = a.detach()
    assert d.stop_gradient
    c = a.clone()
    assert not c.stop_gradient  # clone tracks grad
    c.sum().backward()
    assert a.grad.item() == 1.0


def test_set_value_shape_check():
    t = paddle.ones([2])
    with pytest.raises(ValueError):
        t.set_value(np.zeros((3,), np.float32))


def test_transpose_T():
    t = paddle.to_tensor(np.arange(6).reshape(2, 3).astype(np.float32))
    assert t.T.shape == [3, 2]
    assert paddle.transpose(t, [1, 0]).shape == [3, 2]


def test_default_dtype():
    paddle.set_default_dtype("float32")
    assert paddle.get_default_dtype() == np.dtype(np.float32)
