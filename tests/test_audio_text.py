"""paddle.audio + paddle.text.

Mirrors the reference's `test/legacy_test/test_audio_functions.py` (librosa
parity reduced to closed-form checks), `test_audio_logmel_feature.py`, and
`test_viterbi_decode_op.py` (dynamic-programming result vs brute force).
"""

import itertools
import os
import wave

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio, text


# ------------------------------------------------------------------- audio
def test_mel_scale_round_trip():
    freqs = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0], np.float32)
    for htk in (False, True):
        mel = audio.functional.hz_to_mel(freqs, htk=htk)
        back = audio.functional.mel_to_hz(mel, htk=htk)
        np.testing.assert_allclose(back, freqs, rtol=1e-4, atol=1e-2)
    assert audio.functional.hz_to_mel(1000.0, htk=True) == \
        pytest.approx(1000.0, rel=1e-3)


def test_fbank_matrix_properties():
    fb = audio.functional.compute_fbank_matrix(sr=16000, n_fft=512,
                                               n_mels=40)
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has some support; DC bin is (near) empty
    assert (fb.sum(axis=1) > 0).all()


def test_window_functions():
    for name in ("hann", "hamming", "blackman", "rect"):
        w = audio.functional.get_window(name, 64)
        assert w.shape == (64,)
        assert w.max() <= 1.0 + 1e-6
    with pytest.raises(ValueError):
        audio.functional.get_window("kaiser9000", 64)


def test_spectrogram_detects_tone():
    sr, n_fft = 8000, 256
    t = np.arange(sr, dtype=np.float32) / sr
    tone = np.sin(2 * np.pi * 1000.0 * t)  # 1 kHz
    spec = audio.features.Spectrogram(n_fft=n_fft, hop_length=128)(
        paddle.to_tensor(tone[None, :]))
    s = np.asarray(spec._value)[0]          # (freq, time)
    peak_bin = s.mean(axis=1).argmax()
    want_bin = round(1000.0 / (sr / n_fft))
    assert abs(int(peak_bin) - want_bin) <= 1


def test_logmel_and_mfcc_shapes():
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 8000).astype(np.float32))
    lm = audio.features.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32,
                                          f_min=0.0)(x)
    assert np.asarray(lm._value).shape[0:2] == (2, 32)
    mfcc = audio.features.MFCC(sr=8000, n_mfcc=13, n_mels=32, n_fft=256,
                               f_min=0.0)(x)
    assert np.asarray(mfcc._value).shape[0:2] == (2, 13)
    assert np.isfinite(np.asarray(mfcc._value)).all()


def test_wav_save_load_round_trip(tmp_path):
    sr = 8000
    t = np.arange(sr // 2, dtype=np.float32) / sr
    wav = 0.5 * np.sin(2 * np.pi * 440 * t)[None, :]
    path = str(tmp_path / "tone.wav")
    audio.save(path, paddle.to_tensor(wav), sr)
    meta = audio.info(path)
    assert meta.sample_rate == sr and meta.num_channels == 1
    loaded, sr2 = audio.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(np.asarray(loaded._value), wav, atol=1e-3)


# -------------------------------------------------------------------- text
def brute_force_viterbi(pot, trans_nn, start, stop):
    """Enumerate all tag paths (tiny N, T)."""
    T, N = pot.shape
    best, best_path = -np.inf, None
    for path in itertools.product(range(N), repeat=T):
        s = start[path[0]] + pot[0, path[0]]
        for t in range(1, T):
            s += trans_nn[path[t - 1], path[t]] + pot[t, path[t]]
        s += stop[path[-1]]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    pot = rng.randn(B, T, N).astype(np.float32)
    full = rng.randn(N + 2, N + 2).astype(np.float32)
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(full))
    s_np = np.asarray(scores._value)
    p_np = np.asarray(paths._value)
    for b in range(B):
        want_s, want_p = brute_force_viterbi(
            pot[b], full[:N, :N], full[N, :N], full[:N, N + 1])
        assert s_np[b] == pytest.approx(want_s, rel=1e-5)
        assert list(p_np[b]) == want_p


def test_viterbi_no_bos_eos_and_layer():
    rng = np.random.RandomState(1)
    B, T, N = 2, 4, 3
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    dec = text.ViterbiDecoder(paddle.to_tensor(trans),
                              include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot))
    z = np.zeros(N, np.float32)
    for b in range(2):
        want_s, want_p = brute_force_viterbi(pot[b], trans, z, z)
        assert float(np.asarray(scores._value)[b]) == \
            pytest.approx(want_s, rel=1e-5)
        assert list(np.asarray(paths._value)[b]) == want_p


def test_text_dataset_requires_local_file():
    with pytest.raises(FileNotFoundError):
        text.UCIHousing()


def test_uci_housing_from_local_file(tmp_path):
    rng = np.random.RandomState(0)
    table = rng.rand(50, 14).astype(np.float32)
    f = tmp_path / "housing.data"
    np.savetxt(f, table)
    train = text.UCIHousing(data_file=str(f), mode="train")
    test = text.UCIHousing(data_file=str(f), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_geometric_sample_neighbors_and_reindex():
    """Round-4 geometric depth: CSC neighbor sampling (uniform +
    weighted) and heterogeneous reindex."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import geometric as G

    # CSC graph: node 0 <- {1, 2, 3}, node 1 <- {0}, node 2 <- {}
    row = paddle.to_tensor(np.array([1, 2, 3, 0], np.int64))
    colptr = paddle.to_tensor(np.array([0, 3, 4, 4], np.int64))
    nodes = paddle.to_tensor(np.array([0, 1, 2], np.int64))
    paddle.seed(0)
    nbr, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=2)
    c = cnt.numpy()
    assert list(c) == [2, 1, 0]
    n = nbr.numpy()
    assert set(n[:2]) <= {1, 2, 3} and n[2] == 0
    # eids ride along
    eids = paddle.to_tensor(np.array([10, 11, 12, 13], np.int64))
    _, _, oe = G.sample_neighbors(row, colptr, nodes, sample_size=-1,
                                  eids=eids, return_eids=True)
    assert set(oe.numpy()) == {10, 11, 12, 13}

    # weighted: an overwhelming weight must (a.s.) always be kept
    w = paddle.to_tensor(np.array([1e6, 1e-9, 1e-9, 1.0], np.float32))
    kept = 0
    for s in range(6):
        paddle.seed(s)
        nb, _ = G.weighted_sample_neighbors(row, colptr, w, nodes,
                                            sample_size=1)
        kept += int(nb.numpy()[0] == 1)   # row[0]=1 carries the 1e6 weight
    assert kept == 6

    # heterogeneous reindex: shared compaction over two edge types
    x = paddle.to_tensor(np.array([100, 200], np.int64))
    nb1 = paddle.to_tensor(np.array([300, 100], np.int64))
    c1 = paddle.to_tensor(np.array([1, 1], np.int64))
    nb2 = paddle.to_tensor(np.array([400], np.int64))
    c2 = paddle.to_tensor(np.array([1, 0], np.int64))
    src, dst, out_nodes = G.reindex_heter_graph(x, [nb1, nb2], [c1, c2])
    assert list(out_nodes.numpy()) == [100, 200, 300, 400]
    assert list(src.numpy()) == [2, 0, 3]
    assert list(dst.numpy()) == [0, 1, 0]


def test_wmt14_and_wmt16_datasets(tmp_path):
    """WMT14/WMT16 parse the published tar formats (local-file builds)."""
    import io
    import tarfile
    import numpy as np
    from paddle_tpu.text import WMT14, WMT16

    def add(tf, name, text):
        data = text.encode()
        ti = tarfile.TarInfo(name)
        ti.size = len(data)
        tf.addfile(ti, io.BytesIO(data))

    # WMT14-format tar: dict files + train/train pairs
    p14 = tmp_path / "wmt14.tgz"
    with tarfile.open(p14, "w") as tf:
        add(tf, "wmt14/src.dict", "<s>\n<e>\n<unk>\nhello\nworld\n")
        add(tf, "wmt14/trg.dict", "<s>\n<e>\n<unk>\nbonjour\nmonde\n")
        add(tf, "wmt14/train/train",
            "hello world\tbonjour monde\nhello novel\tbonjour inconnu\n")
    ds = WMT14(data_file=str(p14), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert list(src) == [0, 3, 4, 1]          # <s> hello world <e>
    assert list(trg) == [0, 3, 4]             # <s> bonjour monde
    assert list(trg_next) == [3, 4, 1]        # bonjour monde <e>
    src2, _, _ = ds[1]
    assert list(src2) == [0, 3, 2, 1]         # 'novel' -> <unk>

    # WMT16-format tar: raw pairs; vocab built from data
    p16 = tmp_path / "wmt16.tgz"
    with tarfile.open(p16, "w") as tf:
        add(tf, "wmt16/train",
            "a cat\teine katze\nthe cat\tdie katze\n")
    ds16 = WMT16(data_file=str(p16), mode="train", src_dict_size=10,
                 trg_dict_size=10, lang="en")
    assert len(ds16) == 2
    s0, t0, tn0 = ds16[0]
    assert s0[0] == 0 and s0[-1] == 1         # <s> ... <e>
    assert t0[0] == 0 and tn0[-1] == 1
    # de as source flips the columns
    ds16d = WMT16(data_file=str(p16), mode="train", src_dict_size=10,
                  trg_dict_size=10, lang="de")
    assert "katze" in ds16d.src_dict


def test_audio_dataset_families_label_rules():
    """Round-4 audio datasets: each family's filename->label rule (the
    published naming conventions) plus the synthetic fallback."""
    from paddle_tpu.audio.datasets import (GTZAN, HeySnips, UrbanSound8K,
                                           VoxCeleb)
    g = GTZAN(mode="train", synthetic_size=4)
    assert g._label_of("jazz.00012.wav") == 5
    assert len(g) == 4 and g[0][1] in range(10)
    u = UrbanSound8K(mode="train", synthetic_size=4)
    assert u._label_of("100032-3-0-0.wav") == 3
    h = HeySnips(mode="train", synthetic_size=4)
    assert h._label_of("hey_snips_001.wav") == 1
    assert h._label_of("background_7.wav") == 0
    v = VoxCeleb(mode="train", synthetic_size=4)
    assert v._label_of("id10001_clip1.wav") == 0
    assert v._label_of("id10002_clip1.wav") == 1
    assert v._label_of("id10001_clip2.wav") == 0  # same speaker, same id
