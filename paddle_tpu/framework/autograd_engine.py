"""Tape-free define-by-run autograd engine.

Same design as the reference's eager engine (`fluid/eager/backward.cc:105`
RunBackward, in-degree map at `backward.cc:23`, `fluid/eager/grad_node_info.h:197`
GradNodeBase / `:53` Edge, grad accumulation `fluid/eager/accumulation/`):

* every differentiable op creates one :class:`OpGradNode` holding a VJP
  closure (by default the one returned by ``jax.vjp`` over the op's forward
  function — XLA residuals instead of Paddle's TensorWrapper saves);
* nodes are linked by :class:`Edge` (producer node, output slot);
* leaves get a :class:`GradAccumulationNode` that writes ``tensor.grad``;
* ``backward()`` seeds output grads, BFS-counts in-degrees over the edge
  graph, then walks a ready queue accumulating per-(node, slot) grads.

Grads flow as raw jax Arrays inside the engine; they are wrapped into Tensors
only when stored on leaves or handed to user hooks.
"""

from __future__ import annotations

import weakref
from collections import defaultdict, deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["Edge", "GradNode", "OpGradNode", "GradAccumulationNode", "run_backward"]


class Edge:
    """Connects one input slot of a consumer node to (producer node, out slot)."""

    __slots__ = ("node", "slot")

    def __init__(self, node: "GradNode", slot: int):
        self.node = node
        self.slot = slot


class GradNode:
    """Base grad node: maps output-cotangents -> input-cotangents."""

    op_name: str = "unknown"

    def __init__(self, num_outputs: int):
        self.num_outputs = num_outputs
        # out_meta[i] = (shape, dtype) for constructing zero cotangents of
        # outputs that received no gradient (multi-output ops).
        self.out_meta: List[Optional[Tuple[Tuple[int, ...], Any]]] = [None] * num_outputs
        self.next_edges: List[Optional[Edge]] = []
        # user hooks on this node's *outputs'* grads (tensor.register_hook).
        self.grad_hooks: List[List[Callable]] = [[] for _ in range(num_outputs)]

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        raise NotImplementedError

    def release(self):
        """Drop saved residuals (retain_graph=False path)."""


class OpGradNode(GradNode):
    """Grad node for a registered op; holds the vjp closure + static attrs."""

    __slots__ = ("vjp_fn", "input_treedef", "op_name")

    def __init__(self, op_name: str, num_outputs: int, vjp_fn: Callable):
        super().__init__(num_outputs)
        self.op_name = op_name
        self.vjp_fn = vjp_fn

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        if self.vjp_fn is None:
            raise RuntimeError(
                f"Grad node for op '{self.op_name}' was already released. "
                "Call backward(retain_graph=True) to backprop twice.")
        cot = out_grads[0] if self.num_outputs == 1 else tuple(out_grads)
        in_grads = self.vjp_fn(cot)
        out: List[Optional[Any]] = []
        for g in in_grads:
            out.append(_drop_float0(g))
        return out

    def release(self):
        self.vjp_fn = None


def _drop_float0(g):
    """jax returns float0 cotangents for integer/bool inputs — treat as None."""
    if g is None:
        return None
    if isinstance(g, (list, tuple)):
        return type(g)(_drop_float0(x) for x in g)
    dt = getattr(g, "dtype", None)
    if dt is not None and dt == jax.dtypes.float0:
        return None
    return g


class GradAccumulationNode(GradNode):
    """Leaf sink: accumulates the cotangent into ``tensor.grad``.

    Mirrors `fluid/eager/accumulation/accumulation_node.h`.  Holds a weakref so
    dead leaves don't keep memory alive; also carries reducer hooks used by
    DataParallel (`fluid/distributed/collective/reducer.h:88`).
    """

    op_name = "grad_accumulation"

    def __init__(self, tensor):
        super().__init__(1)
        self._ref = weakref.ref(tensor)
        self.reducer_hooks: List[Callable] = []

    def apply(self, out_grads: List[Any]) -> List[Optional[Any]]:
        t = self._ref()
        g = out_grads[0]
        if t is not None and g is not None:
            t._accumulate_grad(g)
            for hook in self.reducer_hooks:
                hook(t)
        return []


def _zeros_cotangent(meta):
    """Zero cotangent for an output that received no gradient.

    Integer/bool outputs take float0 cotangents (jax.vjp's convention for
    non-differentiable values)."""
    shape, dtype = meta
    if jnp.issubdtype(dtype, jnp.integer) or dtype == jnp.bool_:
        import numpy as _np
        return _np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def run_backward(tensors: Sequence, grad_tensors: Sequence[Optional[Any]],
                 retain_graph: bool = False) -> None:
    """The engine loop — reference: egr::RunBackward (`fluid/eager/backward.cc:105`)."""
    # 1. Seed output grads per (node, slot).
    pending: dict = defaultdict(dict)  # node -> {slot: grad}
    roots: List[GradNode] = []
    for t, g in zip(tensors, grad_tensors):
        node, slot = t._grad_node, t._output_slot
        if node is None:
            if not t.stop_gradient:
                t._accumulate_grad(g)
            continue
        slots = pending[node]
        slots[slot] = g if slot not in slots else slots[slot] + g
        if node not in roots:
            roots.append(node)

    if not roots:
        return

    # 2. In-degree map via BFS over edges (`backward.cc:23` getInDegreeMap).
    indeg: dict = defaultdict(int)
    visited = set()
    queue = deque(roots)
    visited.update(id(n) for n in roots)
    nodes_by_id = {id(n): n for n in roots}
    while queue:
        node = queue.popleft()
        for edge in node.next_edges:
            if edge is None:
                continue
            indeg[id(edge.node)] += 1
            if id(edge.node) not in visited:
                visited.add(id(edge.node))
                nodes_by_id[id(edge.node)] = edge.node
                queue.append(edge.node)

    # 3. Ready-queue walk.
    ready = deque(n for n in roots if indeg[id(n)] == 0)
    processed = set()
    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))

        slot_grads = pending.pop(node, {})
        if not slot_grads and not isinstance(node, GradAccumulationNode):
            # No real gradient reached this node (e.g. only float0 paths):
            # propagate None but still unblock downstream nodes.
            in_grads = [None] * len(node.next_edges)
        else:
            out_grads: List[Any] = []
            for i in range(node.num_outputs):
                g = slot_grads.get(i)
                if g is None and node.out_meta[i] is not None and not isinstance(
                        node, GradAccumulationNode):
                    g = _zeros_cotangent(node.out_meta[i])
                for hook in node.grad_hooks[i]:
                    res = hook(g)
                    if res is not None:
                        g = res
                # AMP: a consumer computing in fp32 sends fp32 cotangents to a
                # low-precision producer — cast to the node's output dtype
                meta = node.out_meta[i]
                if g is not None and meta is not None and \
                        hasattr(g, "dtype") and g.dtype != meta[1] and \
                        jnp.issubdtype(meta[1], jnp.floating) and \
                        g.dtype != jax.dtypes.float0:
                    g = g.astype(meta[1])
                out_grads.append(g)

            in_grads = node.apply(out_grads)
            if not retain_graph:
                node.release()

        for g, edge in zip(in_grads, node.next_edges):
            if edge is None:
                continue
            tgt = edge.node
            if g is not None:
                slots = pending[tgt]
                slots[edge.slot] = g if edge.slot not in slots \
                    else slots[edge.slot] + g
            # Always decrement: a None gradient still resolves the dependency,
            # otherwise nodes reachable only via non-differentiable paths
            # would stall and leaf grads on other paths would be lost.
            indeg[id(tgt)] -= 1
            if indeg[id(tgt)] == 0:
                ready.append(tgt)

    # Flush any leaf accumulation nodes that became ready only via pending
    # (degenerate graphs where an accumulation node still has in-degree > 0
    # because some producer was unreachable — shouldn't happen, but be safe).
    for node, slots in list(pending.items()):
        if isinstance(node, GradAccumulationNode) and indeg[id(node)] <= 0:
            node.apply([slots.get(0)])
