"""Transformer layers. Parity: `python/paddle/nn/layer/transformer.py`.

MultiHeadAttention routes through F.scaled_dot_product_attention so the
Pallas flash kernel is used on TPU when shapes allow."""

from __future__ import annotations

from ...framework.tensor import Tensor
from ...ops import manipulation as _m
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


class MultiHeadAttention(Layer):
    Cache = tuple  # (k, v) decode cache
    StaticCache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, S, E] -> [B, S, H, D]
        b, s = x.shape[0], x.shape[1]
        return _m.reshape(x, [b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value if value is not None else key))
        return (k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = key if key is not None else query
        value = value if value is not None else query
        q = self._shape(self.q_proj(query))
        if cache is not None:
            k_new = self._shape(self.k_proj(key))
            v_new = self._shape(self.v_proj(value))
            k = _m.concat([cache[0], k_new], axis=1)
            v = _m.concat([cache[1], v_new], axis=1)
            new_cache = (k, v)
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            new_cache = None
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            is_causal=False, training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = _m.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, c = mod(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


def _clone_layer(layer):
    """Deep-copy the layer (paddle deep-copies the prototype layer per stack
    slot; every config knob — activation, dropouts, eps — is preserved and
    parameters are NOT shared between clones)."""
    import copy
    return copy.deepcopy(layer)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.norm3 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        for mod in self.layers:
            output = mod(output, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            output = self.norm(output)
        return output


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ...ops.creation import full, tril
        import numpy as np
        m = np.full((length, length), -np.inf, np.float32)
        m = np.triu(m, 1)
        return Tensor(m)
