"""Scrape surface: Prometheus text exposition + the request-trace ring.

ISSUE 6 tentpole (c): render the live metrics registry — counters,
gauges, histograms (cumulative buckets incl. ``+Inf``), quantile
sketches (as summaries) — in Prometheus text exposition format 0.0.4,
the one format every scraper/agent in the monitoring ecosystem ingests.
:mod:`.http` serves it at ``/metrics``; the dump CLI prints it with
``--prom``.

Renaming rules: metric names here use dots (``serving.ttft_seconds``);
Prometheus names must match ``[a-zA-Z_:][a-zA-Z0-9_:]*``, so dots (and
anything else illegal) become underscores — ``serving_ttft_seconds``.
Label values are escaped per the exposition spec (backslash, double
quote, newline).

This module also keeps the bounded ring of per-request serving trace
records (:func:`record_request` / :func:`recent_requests`) that
``/requests`` serves — the scrape-surface twin of the flight recorder's
``kind="request"`` events.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import descriptions as _descriptions
from . import metrics as _metrics

__all__ = ["render_prometheus", "record_request", "recent_requests",
           "clear_requests"]

# the exposition TYPE keyword per registry kind (quantile sketches
# render as Prometheus summaries); unknown kinds are skipped entirely
_TYPE_OF = {"counter": "counter", "gauge": "gauge",
            "histogram": "histogram", "quantile": "summary"}

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v: Any) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels: Dict[str, Any],
                extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(sanitize_name(str(k)), escape_label_value(v))
             for k, v in sorted(labels.items())]
    pairs += [(k, v) for k, v in (extra or [])]
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"


def _series_of(metric) -> List[Tuple[Dict[str, Any], Any]]:
    """Per-kind series snapshot taken UNDER the metric lock: histogram
    raw lists and quantile sketches are live mutable state — a scrape
    racing the serving thread must not tuple-unpack or iterate them
    unlocked (a mid-render _collapse would KeyError the handler)."""
    with metric._lock:
        items = sorted(metric._series.items(), key=lambda kv: repr(kv[0]))
        if metric.kind == "histogram":
            return [(dict(k), (v[0], v[1], v[2], v[3], list(v[4])))
                    for k, v in items]
        if metric.kind == "quantile":
            return [(dict(k), {"quantiles": [(q, v.quantile(q))
                                             for q in metric.quantiles],
                               "sum": v.sum, "count": v.count})
                    for k, v in items]
        return [(dict(k), v) for k, v in items]


def render_prometheus(registry: Optional[_metrics.Registry] = None,
                      name_prefix: str = "") -> str:
    """The registry in text exposition format.  Instruments with no
    recorded series are omitted (same contract as ``snapshot()``).
    ``name_prefix`` is prepended to every sanitized metric name — the
    fleet federation renders its merged registry as ``fleet_*``."""
    if registry is None:
        registry = _metrics._default
    with registry._lock:
        metrics = [registry._metrics[n] for n in sorted(registry._metrics)]
    lines: List[str] = []
    for m in metrics:
        kind = _TYPE_OF.get(m.kind)
        if kind is None:
            continue    # unknown kinds must not emit invalid lines
        series = _series_of(m)
        if not series:
            continue
        name = sanitize_name(name_prefix + m.name)
        # `# HELP` comes from the metric-description registry (explicit
        # describe() wins, instrument help is the auto-registered
        # default); a metric with NO description gets a bare `# TYPE`,
        # never a malformed trailing-space HELP line
        help_text = _descriptions.lookup(m.name) or m.help
        if help_text:
            help_line = help_text.replace("\\", "\\\\") \
                .replace("\n", "\\n")
            lines.append(f"# HELP {name} {help_line}")
        lines.append(f"# TYPE {name} {kind}")
        if m.kind == "counter":
            for labels, v in series:
                lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
        elif m.kind == "gauge":
            for labels, v in series:
                lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
        elif m.kind == "histogram":
            for labels, raw in series:
                count, total, _mn, _mx, bucket_counts = raw
                cum = 0
                for i, bound in enumerate(m.buckets):
                    cum += bucket_counts[i]
                    le = _fmt_value(bound)
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, [('le', le)])} "
                        f"{_fmt_value(cum)}")
                # the +Inf bucket closes the cumulative series at _count
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, [('le', '+Inf')])}"
                    f" {_fmt_value(count)}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} "
                    f"{_fmt_value(count)}")
        elif m.kind == "quantile":
            for labels, snap in series:
                for q, val in snap["quantiles"]:
                    if val is None:
                        continue
                    lines.append(
                        f"{name}"
                        f"{_fmt_labels(labels, [('quantile', _fmt_value(q))])}"
                        f" {_fmt_value(val)}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(snap['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{_fmt_value(snap['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")


# -------------------------------------------------------- request ring

_REQ_CAPACITY = 256
_req_lock = threading.Lock()
_requests: deque = deque(maxlen=_REQ_CAPACITY)


def record_request(record: Dict[str, Any]) -> None:
    """Append one finished/rejected request's trace record (serving
    engine calls this at request finalization; gated there on
    ``FLAGS_enable_metrics``)."""
    with _req_lock:
        _requests.append(dict(record, unix_time=round(time.time(), 3)))


def recent_requests(n: int = 64) -> List[Dict[str, Any]]:
    """Last ``n`` request trace records, newest last (the ``/requests``
    endpoint's payload)."""
    n = int(n)
    if n <= 0:
        return []        # items[-0:] would be the WHOLE ring
    with _req_lock:
        items = list(_requests)
    return items[-n:]


def clear_requests() -> None:
    with _req_lock:
        _requests.clear()
