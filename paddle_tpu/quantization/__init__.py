"""Model quantization: QAT fake-quant + PTQ observers.

Parity: `python/paddle/quantization/` — QuantConfig (config.py), QAT
(qat.py), PTQ (ptq.py), FakeQuanterWithAbsMaxObserver (quanters/abs_max.py),
AbsmaxObserver (observers/abs_max.py), QuantedLinear
(nn/quant/qat/linear.py).
"""

from .config import QuantConfig
from .observers import AbsmaxObserver
from .ptq import PTQ
from .qat import QAT, QuantedLinear
from .quanters import (FakeQuanterWithAbsMaxObserver, fake_quantize_absmax,
                       quantize_dequantize)
from .weight_only import (dequantize, dequantize_int8,
                          quantize_absmax_fp8, quantize_absmax_int8)

__all__ = ["QuantConfig", "QAT", "PTQ", "QuantedLinear", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver", "fake_quantize_absmax",
           "quantize_dequantize", "quantize_absmax_int8",
           "quantize_absmax_fp8", "dequantize", "dequantize_int8"]
