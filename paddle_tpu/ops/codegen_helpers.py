"""Hand-written lowerings referenced from specs/ops.yaml (the reference's
equivalent is the manual kernels its YAML entries name)."""

from __future__ import annotations

import jax.numpy as jnp


def diag_embed(x, *, offset=0, dim1=-2, dim2=-1):
    """Batched diagonal embedding (`tensor/creation.py` diag_embed):
    the last dim of x becomes the (offset) diagonal of a matrix whose two
    new axes land at output positions (dim1, dim2)."""
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = base.at[..., rows, cols].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    return jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))


def logcumsumexp(x, *, axis=-1):
    """lax.cumlogsumexp with python-style axis normalization (lax rejects
    negative axes)."""
    import jax
    return jax.lax.cumlogsumexp(x, axis=axis % x.ndim)
