"""Audio feature layers: Spectrogram / MelSpectrogram / LogMelSpectrogram /
MFCC.

Parity: `python/paddle/audio/features/layers.py`.

TPU-native: the STFT is a strided framing (gather) + window multiply +
rfft; mel projection and DCT are matmuls — one fused XLA pipeline per
batch of waveforms.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.registry import dispatch as _d, register_op
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _stft_impl(x, window, n_fft=512, hop_length=None, win_length=None,
               center=True, pad_mode="reflect", power=2.0):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    n_frames = 1 + (x.shape[-1] - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = x[..., idx]                       # (..., n_frames, n_fft)
    frames = frames * window[None, :]
    spec = jnp.fft.rfft(frames, axis=-1)       # (..., n_frames, 1+n_fft//2)
    mag = jnp.abs(spec) ** power
    return jnp.swapaxes(mag, -1, -2)           # (..., freq, time)


register_op("stft_power", _stft_impl)


class Spectrogram(Layer):
    """Power spectrogram.  Parity: `features/layers.py` Spectrogram."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - self.win_length) // 2
            w = np.pad(w, (lp, n_fft - self.win_length - lp))
        self.register_buffer("window", paddle.to_tensor(w),
                             persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        return _d("stft_power", (x, self.window),
                  {"n_fft": self.n_fft, "hop_length": self.hop_length,
                   "win_length": self.win_length, "center": self.center,
                   "pad_mode": self.pad_mode, "power": self.power})


class MelSpectrogram(Layer):
    """Parity: `features/layers.py` MelSpectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                        htk, norm)
        self.register_buffer("fbank", paddle.to_tensor(fbank),
                             persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        spec = self.spectrogram(x)              # (..., freq, time)
        return paddle.matmul(self.fbank, spec)  # (..., n_mels, time)


class LogMelSpectrogram(Layer):
    """Parity: `features/layers.py` LogMelSpectrogram."""

    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 **mel_kwargs):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **mel_kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x: Tensor) -> Tensor:
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    """Parity: `features/layers.py` MFCC (log-mel -> DCT)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 **logmel_kwargs):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels,
                                        **logmel_kwargs)
        dct = AF.create_dct(n_mfcc, n_mels)
        self.register_buffer("dct", paddle.to_tensor(dct),
                             persistable=False)

    def forward(self, x: Tensor) -> Tensor:
        lm = self.logmel(x)                           # (..., n_mels, time)
        return paddle.matmul(self.dct, lm, transpose_x=True)
