"""Test-support utilities shipped with the package (deterministic fault
injection for the fault-tolerance suite and the bench resilience rung)."""

from . import chaos  # noqa: F401

__all__ = ["chaos"]
