"""ProcessMesh. Parity: `python/paddle/distributed/auto_parallel/
process_mesh.py` / C++ `phi/core/distributed/auto_parallel/process_mesh.h`.

Wraps (and can create) the global jax Mesh; `dim_names` become mesh axis
names used by placements."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .. import mesh as _mesh

__all__ = ["ProcessMesh"]


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._process_ids = arr.reshape(-1).tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def processes(self):
        return self._process_ids

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        idx = self._process_ids.index(process_id)
        coord = np.unravel_index(idx, tuple(self._shape))
        return int(coord[self._dim_names.index(dim)] if isinstance(dim, str)
                   else coord[dim])

    def get_mesh_with_dim(self, dim_name):
        return self

    @property
    def mesh(self):
        return np.asarray(self._process_ids).reshape(self._shape)

    def jax_mesh(self) -> Mesh:
        """Materialize as a jax Mesh over the actual devices."""
        if self._jax_mesh is None:
            devices = jax.devices()
            picked = [devices[i % len(devices)] for i in self._process_ids]
            arr = np.asarray(picked).reshape(tuple(self._shape))
            self._jax_mesh = Mesh(arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._process_ids == other._process_ids
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((tuple(self._shape), tuple(self._process_ids),
                     tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")
