"""Tensor-parallel serving programs over the device mesh.

Role of the paper's target deployment (GPT-3-class decode on a v5p pod;
SNIPPETS [1]-[3] mesh/NamedSharding patterns): the serving engine's
tick/prefill/decode programs become ``shard_map`` programs over a 'tp'
axis of `distributed/mesh.py`, with weights sharded Megatron-style
(attention heads + FFN/vocab columns) and the paged KV pools sharded
along the HEAD axis.  The host scheduler stays rank-0: block tables,
seq_lens and sampling params are broadcast (replicated inputs), so none
of the scheduler logic changes with the degree.

BIT-PARITY CONTRACT.  TP decode at any degree is bit-identical to
degree 1 because no contraction dimension is ever split:

* every matmul is COLUMN-parallel (output dim sharded) — a local shard
  computes exact column slices of the full matmul, reducing over the
  same elements in the same order;
* attention is per-head independent (heads sharded = batch-like dim);
* activations are re-replicated between matmuls by ``all_gather``
  (deterministic concatenation in device order), never by summing
  partial products (the classic row-parallel all-reduce REORDERS the
  float reduction and loses bitwise parity — on a decode tick the
  gathered activations are tiny, so the extra bytes are noise);
* the vocab-parallel embedding lookup psums one nonzero contribution
  against exact zeros (x + 0.0 == x).

The price is a little more communication volume than an all-reduce
formulation; the win is that greedy streams, the warmup grid and every
parity test are IDENTICAL across degrees — the property the serving
tests pin on a simulated 2-4 device mesh.

Scope: GPT-family models (`models/gpt.py` — pre-LN blocks, fused QKV,
gelu MLP, tied vocab head).  Anything else raises a clear error and
serves at degree 1.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.kv_cache import PagedKVCache

__all__ = ["TPPlan", "build_plan", "shard_plan", "forward_tp",
           "pool_spec", "AXIS"]

AXIS = "tp"


class TPPlan:
    """Host-side description of one model's TP layout: the reshaped
    parameter pytree (qkv as [H, 3, nh, hd] so the head axis is
    shardable), the matching PartitionSpec pytree, and the static dims
    the forward needs."""

    def __init__(self, params: Dict[str, Any], specs: Dict[str, Any],
                 meta: Dict[str, Any]):
        self.params = params
        self.specs = specs
        self.meta = meta


def _leaf(p):
    return p._value if hasattr(p, "_value") else jnp.asarray(p)


def build_plan(model, tp: int) -> TPPlan:
    """Extract + validate the GPT-family parameter layout for degree
    ``tp``.  Raises ValueError for unsupported structures (MoE blocks,
    GQA-free requirement is implicit in the GPT family, dims that do not
    divide the degree)."""
    gpt = getattr(model, "gpt", None)
    cfg = getattr(model, "cfg", None)
    if gpt is None or cfg is None or not hasattr(gpt, "blocks") \
            or not hasattr(gpt, "wte") or not hasattr(gpt, "wpe"):
        raise ValueError(
            "FLAGS_serving_tp_degree > 1 supports GPT-family models "
            f"(got {type(model).__name__}); serve this model at degree 1")
    if getattr(cfg, "moe_num_experts", 0):
        raise ValueError("tensor-parallel serving does not cover MoE "
                         "blocks; serve at degree 1")
    if getattr(cfg, "tensor_parallel", False):
        raise ValueError(
            "model was built with tensor_parallel=True (training-style "
            "mesh sharding); the serving TP path owns its own layout — "
            "build the model with tensor_parallel=False")
    nh, H, V = cfg.num_heads, cfg.hidden_size, cfg.vocab_size
    I = cfg.intermediate_size  # noqa: E741
    for name, dim in (("num_heads", nh), ("intermediate_size", I),
                      ("vocab_size", V)):
        if dim % tp:
            raise ValueError(
                f"serving_tp_degree={tp} must divide {name}={dim}")
    hd = H // nh
    blocks: List[Dict[str, Any]] = []
    specs_blocks: List[Dict[str, Any]] = []
    for blk in gpt.blocks:
        attn, mlp = blk.attn, blk.mlp
        for attr in ("qkv", "proj"):
            if not hasattr(attn, attr):
                raise ValueError("unsupported attention layout for TP "
                                 f"serving: missing attn.{attr}")
        if not hasattr(mlp, "fc1") or not hasattr(mlp, "fc2"):
            raise ValueError("unsupported MLP layout for TP serving "
                             "(expected fc1/fc2)")
        blocks.append({
            "ln1_w": _leaf(blk.ln1.weight), "ln1_b": _leaf(blk.ln1.bias),
            "qkv_w": _leaf(attn.qkv.weight).reshape(H, 3, nh, hd),
            "qkv_b": _leaf(attn.qkv.bias).reshape(3, nh, hd),
            "proj_w": _leaf(attn.proj.weight),
            "proj_b": _leaf(attn.proj.bias),
            "ln2_w": _leaf(blk.ln2.weight), "ln2_b": _leaf(blk.ln2.bias),
            "fc1_w": _leaf(mlp.fc1.weight), "fc1_b": _leaf(mlp.fc1.bias),
            "fc2_w": _leaf(mlp.fc2.weight), "fc2_b": _leaf(mlp.fc2.bias),
        })
        specs_blocks.append({
            "ln1_w": P(), "ln1_b": P(),
            "qkv_w": P(None, None, AXIS, None),
            "qkv_b": P(None, AXIS, None),
            "proj_w": P(None, AXIS), "proj_b": P(AXIS),
            "ln2_w": P(), "ln2_b": P(),
            "fc1_w": P(None, AXIS), "fc1_b": P(AXIS),
            "fc2_w": P(None, AXIS), "fc2_b": P(AXIS),
        })
    params = {"wte": _leaf(gpt.wte.weight), "wpe": _leaf(gpt.wpe.weight),
              "blocks": blocks,
              "lnf_w": _leaf(gpt.ln_f.weight),
              "lnf_b": _leaf(gpt.ln_f.bias)}
    specs = {"wte": P(AXIS, None), "wpe": P(),
             "blocks": specs_blocks, "lnf_w": P(), "lnf_b": P()}
    meta = {"tp": int(tp), "nh": nh, "hd": hd, "H": H, "V": V,
            "V_local": V // tp, "n_layers": cfg.num_layers,
            "ln_eps": [(float(blk.ln1._epsilon), float(blk.ln2._epsilon))
                       for blk in gpt.blocks],
            "lnf_eps": float(gpt.ln_f._epsilon)}
    return TPPlan(params, specs, meta)


def pool_spec():
    """Paged KV pools [nh, num_blocks, bs, hd] shard along the leading
    HEAD axis — each rank holds its heads' blocks of every layer."""
    return P(AXIS)


def shard_plan(plan: TPPlan, mesh) -> Dict[str, Any]:
    """Place the plan's parameters on the mesh with their NamedShardings
    (the TP memory win: each rank holds 1/tp of every sharded matrix);
    returns the device-resident pytree the programs take as input.

    Manual recursion rather than tree_map: PartitionSpec subclasses
    tuple, so a tree_map over the spec tree would recurse INTO the
    specs instead of treating them as leaves."""
    def place(p, s):
        if isinstance(p, dict):
            return {k: place(p[k], s[k]) for k in p}
        if isinstance(p, list):
            return [place(a, b) for a, b in zip(p, s)]
        return jax.device_put(jnp.asarray(p), NamedSharding(mesh, s))
    return place(plan.params, plan.specs)


def _w(leaf):
    """Weight-only quantized leaves (``{"q", "s"}`` pairs installed by
    `inference/quant.quantize_plan`) dequantize IN-TRACE right before
    their matmul — XLA fuses the per-channel scale multiply into the
    contraction, so device weight residency stays the storage format
    (int8 codes or fp8 e4m3fn — `dequantize` is format-agnostic).  The
    scale was computed per channel BEFORE sharding and keeps its
    reduced axis, so each rank's (q, s) shard dequantizes
    bit-identically to a slice of the full dequantized matrix — either
    quant mode composes with the TP bit-parity contract."""
    if isinstance(leaf, dict):
        from ..quantization.weight_only import dequantize
        return dequantize(leaf["q"], leaf["s"])
    return leaf


def _layer_norm(x, w, b, eps):
    # exact mirror of nn/functional/norm.py::_layer_norm_impl over the
    # last axis (the only shape GPT uses) — parity with degree 1 demands
    # the same expression, not an equivalent one
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * w + b


def forward_tp(meta, params, ids, pools, tables, seq_lens, pos_offset,
               block_size, view_cls=PagedKVCache):
    """One forward over the LOCAL shards — runs inside ``shard_map``.

    ids [B, s] / tables / seq_lens / pos_offset are replicated; params
    and the per-layer (k, v) ``pools`` are this rank's shards.  Returns
    (full [B, s, V] logits — replicated via the final vocab all-gather —
    and the new local pools).  ``view_cls`` selects the cache semantics:
    `PagedKVCache` (decode / from-empty prefill) or `PagedChunkView`
    (prefix-cache suffix prefill)."""
    B, s = ids.shape
    idx = jax.lax.axis_index(AXIS)
    nh, hd, tp = meta["nh"], meta["hd"], meta["tp"]
    nh_l = nh // tp
    Vl = meta["V_local"]
    # vocab-parallel embedding: one rank contributes the row, the psum
    # adds exact zeros elsewhere
    v0 = (idx * Vl).astype(ids.dtype)
    in_range = (ids >= v0) & (ids < v0 + Vl)
    wte = _w(params["wte"])   # also the tied head below
    rows = jnp.take(wte, jnp.clip(ids - v0, 0, Vl - 1), axis=0)
    rows = jnp.where(in_range[..., None], rows, 0)
    pos = jnp.arange(s, dtype=jnp.int32) + pos_offset
    x = jax.lax.psum(rows, AXIS) + jnp.take(params["wpe"], pos, axis=0)

    def gather(h):
        return jax.lax.all_gather(h, AXIS, axis=-1, tiled=True)

    new_pools = []
    for li, blk in enumerate(params["blocks"]):
        eps1, eps2 = meta["ln_eps"][li]
        h = _layer_norm(x, blk["ln1_w"], blk["ln1_b"], eps1)
        qkv = jnp.matmul(h, _w(blk["qkv_w"]).reshape(
            meta["H"], 3 * nh_l * hd)) \
            + blk["qkv_b"].reshape(3 * nh_l * hd)
        qkv = qkv.reshape(B, s, 3, nh_l, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        kp, vp = pools[li]
        view = view_cls.from_parts(kp, vp, tables, seq_lens, block_size)
        new_view, out = view.update_and_attend(q, k, v)
        new_pools.append((new_view.k, new_view.v))
        out = gather(out.reshape(B, s, nh_l * hd))        # heads -> full
        y = gather(jnp.matmul(out, _w(blk["proj_w"])) + blk["proj_b"])
        x = x + y
        h2 = _layer_norm(x, blk["ln2_w"], blk["ln2_b"], eps2)
        a = gather(jax.nn.gelu(
            jnp.matmul(h2, _w(blk["fc1_w"])) + blk["fc1_b"],
            approximate=True))
        x = x + gather(jnp.matmul(a, _w(blk["fc2_w"])) + blk["fc2_b"])
    h = _layer_norm(x, params["lnf_w"], params["lnf_b"], meta["lnf_eps"])
    logits = gather(jnp.matmul(h, jnp.swapaxes(wte, -1, -2)))
    return logits, new_pools
