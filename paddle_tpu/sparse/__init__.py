"""paddle.sparse — COO/CSR sparse tensors and ops.

Parity: `python/paddle/sparse/` (creation.py sparse_coo_tensor/
sparse_csr_tensor, unary/binary ops, matmul, nn.ReLU) and
`paddle/phi/core/sparse_coo_tensor.h` / `sparse_csr_tensor.h`.

TPU-native: storage is `jax.experimental.sparse` BCOO (the XLA-lowerable
batched-COO format); CSR creation converts to BCOO internally (XLA has no
CSR kernels — crow/col views are materialised on demand for API parity).
Dense results come back as regular paddle Tensors.
"""

from . import nn  # noqa: F401
from .binary import add, matmul, multiply, subtract
from .creation import (SparseCooTensor, SparseCsrTensor, sparse_coo_tensor,
                       sparse_csr_tensor)
from .unary import abs, cast, neg, pow, relu, sin, sqrt, square, tanh  # noqa: A004

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "add", "subtract", "multiply", "matmul",
           "relu", "abs", "neg", "sin", "tanh", "sqrt", "square", "pow",
           "cast", "nn"]
