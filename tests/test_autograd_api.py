"""PyLayer / paddle.grad / jacobian / recompute tests.

Parity targets: `test/legacy_test/test_pylayer_op.py`,
`test/legacy_test/test_imperative_double_grad.py`,
`test/collective/fleet/test_dygraph_recompute.py` patterns.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.autograd import (PyLayer, grad, hessian, jacobian,
                                 saved_tensors_hooks)
from paddle_tpu.distributed.fleet import recompute


class CubeLayer(PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = x * x * x
        ctx.save_for_backward(x)
        return y

    @staticmethod
    def backward(ctx, dy):
        (x,) = ctx.saved_tensor()
        return 3.0 * x * x * dy


def test_pylayer_custom_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = CubeLayer.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               3.0 * np.array([1.0, 4.0, 9.0]), rtol=1e-6)


class ScaleGrad(PyLayer):
    """backward intentionally differs from the true vjp -> proves the
    custom backward replaces the inner graph."""

    @staticmethod
    def forward(ctx, x):
        return x * 2.0

    @staticmethod
    def backward(ctx, dy):
        return dy * 100.0


def test_pylayer_overrides_inner_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    ScaleGrad.apply(x).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [100.0] * 3)


class TwoInTwoOut(PyLayer):
    @staticmethod
    def forward(ctx, a, b):
        ctx.save_for_backward(a, b)
        return a + b, a * b

    @staticmethod
    def backward(ctx, d_sum, d_prod):
        a, b = ctx.saved_tensor()
        return d_sum + d_prod * b, d_sum + d_prod * a


def test_pylayer_multi_io():
    a = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.array([5.0], np.float32), stop_gradient=False)
    s, p = TwoInTwoOut.apply(a, b)
    (s + p).backward()
    np.testing.assert_allclose(np.asarray(a.grad._value), [6.0])  # 1 + b
    np.testing.assert_allclose(np.asarray(b.grad._value), [3.0])  # 1 + a


def test_pylayer_inside_jit_capture():
    from paddle_tpu.jit import to_static
    lin = nn.Linear(4, 4)

    def step(x):
        h = lin(x)
        return CubeLayer.apply(h).sum()

    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4)
                         .astype(np.float32))
    eager = float(step(x).item())
    jitted = float(to_static(step)(x).item())
    np.testing.assert_allclose(jitted, eager, rtol=1e-5)


def test_saved_tensors_hooks():
    packed = []

    def pack(t):
        packed.append(t)
        return len(packed) - 1

    def unpack(i):
        return packed[i]

    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    with saved_tensors_hooks(pack, unpack):
        y = CubeLayer.apply(x)
    y.backward()
    assert len(packed) == 1
    np.testing.assert_allclose(np.asarray(x.grad._value), [12.0])


# ------------------------------------------------------------------ grad()

def test_grad_basic_no_side_effect():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (gx,) = grad(y, x)
    np.testing.assert_allclose(np.asarray(gx._value), [6.0])
    assert x.grad is None  # .grad untouched


def test_grad_multi_in_out_and_unused():
    a = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.array([4.0], np.float32), stop_gradient=False)
    c = paddle.to_tensor(np.array([9.0], np.float32), stop_gradient=False)
    y1 = a * b
    y2 = a + 1.0
    ga, gb, gc = grad([y1, y2], [a, b, c], allow_unused=True)
    np.testing.assert_allclose(np.asarray(ga._value), [5.0])  # b + 1
    np.testing.assert_allclose(np.asarray(gb._value), [2.0])  # a
    assert gc is None
    with pytest.raises(RuntimeError):
        y3 = a * 2.0
        grad(y3, c)


def test_grad_wrt_intermediate():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    h = x * 3.0
    y = h * h
    (gh,) = grad(y, h, retain_graph=True)
    np.testing.assert_allclose(np.asarray(gh._value), [12.0])  # 2h


def test_double_grad_create_graph():
    # d/dx (x^3) = 3x^2; d2/dx2 = 6x
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * x * x
    (gx,) = grad(y, x, create_graph=True)
    np.testing.assert_allclose(np.asarray(gx._value), [12.0])
    (ggx,) = grad(gx, x)
    np.testing.assert_allclose(np.asarray(ggx._value), [12.0])  # 6x


def test_double_grad_through_network():
    paddle.seed(0)
    lin = nn.Linear(3, 1)
    x = paddle.to_tensor(np.random.RandomState(1).rand(2, 3)
                         .astype(np.float32), stop_gradient=False)
    y = paddle.tanh(lin(x)).sum()
    (gx,) = grad(y, x, create_graph=True)
    gp = grad(gx.sum(), lin.weight)  # grad of grad wrt weight exists
    assert gp[0] is not None and gp[0].shape == [3, 1]


def test_jacobian_hessian():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    jac = jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(np.asarray(jac._value),
                               np.diag([2.0, 4.0]), rtol=1e-6)
    hes = hessian(lambda t: (t * t * t).sum(), x)
    np.testing.assert_allclose(np.asarray(hes._value),
                               np.diag([6.0, 12.0]), rtol=1e-6)


# -------------------------------------------------------------- recompute()

def test_recompute_matches_plain():
    paddle.seed(4)
    block = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8))
    x = paddle.to_tensor(np.random.RandomState(2).rand(4, 8)
                         .astype(np.float32), stop_gradient=False)

    out_rc = recompute(block, x)
    out_rc.sum().backward()
    g_rc = np.asarray(block[0].weight.grad._value)
    gx_rc = np.asarray(x.grad._value)

    block.clear_gradients()
    x2 = paddle.to_tensor(np.asarray(x._value), stop_gradient=False)
    out = block(x2)
    np.testing.assert_allclose(np.asarray(out_rc._value),
                               np.asarray(out._value), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(g_rc, np.asarray(block[0].weight.grad._value),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gx_rc, np.asarray(x2.grad._value),
                               rtol=1e-5, atol=1e-6)


def test_recompute_gpt_model_parity():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    def run(rc):
        paddle.seed(11)
        model = GPTForCausalLM(gpt3_tiny(use_recompute=rc))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 1024, (2, 32)).astype("int32"))
        loss = model.compute_loss(ids, ids)
        loss.backward()
        return (float(loss.item()),
                np.asarray(model.gpt.blocks[0].mlp.fc1.weight.grad._value))

    l0, g0 = run(False)
    l1, g1 = run(True)
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    np.testing.assert_allclose(g1, g0, rtol=1e-4, atol=1e-6)


def test_recompute_under_jit_capture():
    from paddle_tpu.jit import to_static
    from paddle_tpu import optimizer
    paddle.seed(12)
    block = nn.Sequential(nn.Linear(8, 8), nn.Tanh())
    opt = optimizer.SGD(learning_rate=0.1, parameters=block.parameters())

    def step(x):
        loss = recompute(block, x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.random.RandomState(3).rand(2, 8)
                         .astype(np.float32))
    jitted = to_static(step)
    l0 = float(jitted(x).item())
    l1 = float(jitted(x).item())
    assert l1 < l0  # trains under capture


def test_double_grad_with_int_input_op():
    """Embedding (int indices -> float0 cotangent slot) under create_graph:
    the second backward must materialize structure-matching float0s."""
    from paddle_tpu.nn import functional as F
    w = paddle.to_tensor(np.random.RandomState(5).rand(8, 4)
                         .astype(np.float32), stop_gradient=False)
    idx = paddle.to_tensor(np.array([1, 3], np.int32))
    out = (F.embedding(idx, w) * F.embedding(idx, w)).sum()
    (gw,) = grad(out, [w], create_graph=True)
    gw.sum().backward()
    assert w.grad is not None
    # d/dw sum(2*onehot-rows * w) = 2 at the selected rows
    expect = np.zeros((8, 4), np.float32)
    expect[[1, 3]] = 2.0
    np.testing.assert_allclose(np.asarray(w.grad._value), expect, rtol=1e-5)


def test_saved_tensors_hooks_tensor_pack():
    """pack returning a Tensor (bf16 compression) still runs unpack."""
    dtypes_seen = []

    class Probe(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2.0

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            dtypes_seen.append(str(x.dtype))
            return dy * 2.0

    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    with saved_tensors_hooks(lambda t: t.astype("bfloat16"),
                             lambda t: t.astype("float32")):
        y = Probe.apply(x)
    y.sum().backward()
    assert dtypes_seen == ["paddle.float32"] or "float32" in dtypes_seen[0]


def test_grad_prunes_unrelated_subgraph():
    """grad(loss, intermediate) must not execute vjps below the input."""
    from paddle_tpu.ops import registry
    calls = {}
    sink, registry._op_stats_sink = registry._op_stats_sink, calls
    try:
        lin1 = nn.Linear(4, 4)
        lin2 = nn.Linear(4, 4)
        x = paddle.to_tensor(np.random.RandomState(6).rand(2, 4)
                             .astype(np.float32))
        h = lin1(x)
        y = lin2(h).sum()
        calls.clear()
        (gh,) = grad(y, h)
        assert gh is not None
        # pruning: no vjp dispatch happens in non-create_graph mode anyway;
        # assert instead that lin1's weight never got a grad
        assert lin1.weight.grad is None and lin2.weight.grad is None
    finally:
        registry._op_stats_sink = sink


def test_incubate_autograd_jvp_vjp_forward_grad():
    """Round-4 incubate.autograd (functional.py jvp:27 / vjp:91 +
    primapi forward_grad): forward- and reverse-mode functionals over
    paddle Tensors via jax's native transforms."""
    import numpy as np
    import paddle_tpu as paddle
    inc = paddle.incubate
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    out, dot = inc.autograd.jvp(lambda t: t * t, x)
    np.testing.assert_allclose(out.numpy(), [1.0, 4.0, 9.0])
    np.testing.assert_allclose(dot.numpy(), [2.0, 4.0, 6.0])
    # directional tangent
    v = paddle.to_tensor(np.array([0.0, 1.0, 0.0], np.float32))
    _, dv = inc.autograd.jvp(lambda t: t * t, x, v)
    np.testing.assert_allclose(dv.numpy(), [0.0, 4.0, 0.0])
    out, grad = inc.autograd.vjp(lambda t: (t ** 3).sum(), x)
    np.testing.assert_allclose(grad.numpy(), 3 * np.array([1, 4, 9.0]),
                               rtol=1e-6)
    fg = inc.autograd.forward_grad(lambda t: paddle.sin(t), x, v)
    np.testing.assert_allclose(fg.numpy(), np.cos([1, 2, 3.0])
                               * np.array([0, 1, 0.0]), rtol=1e-6)
    # multi-input jvp
    y = paddle.to_tensor(np.array([2.0], np.float32))
    _, d2 = inc.autograd.jvp(lambda a, b: a * b, [x, y])
    np.testing.assert_allclose(d2.numpy(), x.numpy() + y.numpy(),
                               rtol=1e-6)


def test_incubate_jit_inference_decorator():
    import numpy as np
    import paddle_tpu as paddle

    @paddle.incubate.jit.inference
    def head(t):
        return t * 2.0 + 1.0

    x = paddle.to_tensor(np.ones(3, np.float32))
    out = head(x)
    np.testing.assert_allclose(out.numpy(), [3.0, 3.0, 3.0])
    assert out.stop_gradient  # ran under no_grad
