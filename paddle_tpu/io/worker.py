"""Multiprocess DataLoader workers with shared-memory batch transport.

Parity: `python/paddle/io/dataloader/worker.py` (_worker_loop) +
`paddle/fluid/memory/allocation/mmap_allocator.cc` (the reference moves
batches between workers and the trainer through shared memory; here the
payload rides `multiprocessing.shared_memory` blocks and only metadata
crosses the queue).

Workers are SPAWNED (never forked): JAX/XLA holds native threads in the
parent, and a forked child inheriting them can deadlock.  Workers collate
to numpy; the parent turns arrays into device Tensors — so the host-side
decode/augment runs on all cores while the chip trains.
"""

from __future__ import annotations

import traceback
from multiprocessing import shared_memory
from typing import Any, List

import numpy as np

__all__ = ["worker_loop", "pack_batch", "unpack_batch", "numpy_collate"]


def numpy_collate(batch: List[Any]):
    """Stack samples into numpy arrays, mirroring default_collate's
    structure handling (tuple/list/dict of arrays/scalars)."""
    first = batch[0]
    if isinstance(first, np.ndarray):
        return np.stack(batch)
    # dtype parity with io.default_collate_fn: int -> int64, float -> f32
    if isinstance(first, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(first, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(first, (tuple, list)):
        return type(first)(numpy_collate(list(col)) for col in zip(*batch))
    if isinstance(first, dict):
        return {k: numpy_collate([d[k] for d in batch]) for k in first}
    # strings / arbitrary objects pass through as a list
    return list(batch)


def _to_numpy_tree(obj):
    """Convert any paddle Tensors a custom collate_fn produced to numpy."""
    tname = type(obj).__name__
    if tname in ("Tensor", "Parameter") and hasattr(obj, "_value"):
        return np.asarray(obj._value)
    if isinstance(obj, (tuple, list)):
        return type(obj)(_to_numpy_tree(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    return obj


def pack_batch(batch, use_shared_memory: bool):
    """Replace large ndarrays with shared-memory descriptors.

    Returns (payload, shm_blocks): payload is queue-safe metadata; the
    worker must keep `shm_blocks` alive until the parent confirms receipt
    (we close immediately after put — the parent re-attaches by name and
    unlinks)."""
    blocks = []

    def pack(x):
        if isinstance(x, np.ndarray) and use_shared_memory and x.nbytes > 0:
            shm = shared_memory.SharedMemory(create=True, size=x.nbytes)
            view = np.ndarray(x.shape, x.dtype, buffer=shm.buf)
            view[...] = x
            blocks.append(shm)
            return ("__shm__", shm.name, x.shape, str(x.dtype))
        if isinstance(x, np.ndarray):
            return ("__np__", x)
        if isinstance(x, (tuple, list)):
            return ("__seq__", type(x).__name__, [pack(v) for v in x])
        if isinstance(x, dict):
            return ("__map__", {k: pack(v) for k, v in x.items()})
        return ("__obj__", x)

    return pack(batch), blocks


def unpack_batch(payload):
    """Inverse of pack_batch (parent side); unlinks consumed shm blocks."""
    tag = payload[0]
    if tag == "__shm__":
        _, name, shape, dtype = payload
        shm = shared_memory.SharedMemory(name=name)
        try:
            arr = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf).copy()
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return arr
    if tag == "__np__":
        return payload[1]
    if tag == "__seq__":
        seq = [unpack_batch(v) for v in payload[2]]
        return tuple(seq) if payload[1] == "tuple" else seq
    if tag == "__map__":
        return {k: unpack_batch(v) for k, v in payload[1].items()}
    return payload[1]


def worker_loop(dataset, index_queue, result_queue, collate_fn,
                use_shared_memory: bool, worker_init_fn, worker_id: int,
                num_workers: int = 0):
    """Worker main: pull index lists, collate, ship via shared memory."""
    try:
        # publish worker identity so get_worker_info()-sharded datasets and
        # worker_init_fns see who they are (reference worker.py does the
        # same before init_fn)
        from .. import io as _io
        _io._worker_info = _io._WorkerInfo(worker_id, num_workers, dataset)
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
    except BaseException:
        result_queue.put(("error", worker_id, traceback.format_exc()))
        return
    while True:
        job = index_queue.get()
        if job is None:
            result_queue.put(("done", worker_id, None))
            return
        seq, indices = job
        try:
            samples = [dataset[i] for i in indices]
            if collate_fn is not None:
                batch = _to_numpy_tree(collate_fn(samples))
            else:
                batch = numpy_collate([_to_numpy_tree(s) for s in samples])
            payload, blocks = pack_batch(batch, use_shared_memory)
            result_queue.put(("batch", seq, payload))
            for b in blocks:
                b.close()  # parent re-attaches by name and unlinks
        except BaseException:
            result_queue.put(("error", worker_id, traceback.format_exc()))
            return
