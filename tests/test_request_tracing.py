"""Request lifecycle tracing + recompile blame on the serving engine
(ISSUE 6 tentpole): TTFT/TPOT/e2e/queue-wait sketches, SLO counters,
scheduler-pressure gauges, per-request trace records in the flight ring
and the /requests export ring, compile-tracker blame for shape-driven
recompiles, and the acceptance scrape — a running engine answering
GET /metrics with `serving_ttft_seconds` quantiles and
`compile_seconds_total`."""

import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability import (compile_tracker, export,
                                      flight_recorder, metrics)
from paddle_tpu.observability import http as obs_http


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    compile_tracker.reset()
    export.clear_requests()
    flight_recorder.default_recorder().clear()
    yield
    paddle.set_flags({"enable_metrics": True})
    metrics.reset()
    compile_tracker.reset()
    export.clear_requests()
    obs_http.stop()


def _mk(rng, plen, n):
    return Request(rng.randint(1, 1000, (plen,)), max_new_tokens=n)


def test_ttft_tpot_e2e_traces(model):
    """Every finished request contributes exactly one TTFT/e2e/queue-wait
    observation and per-token TPOT observations; stats() exposes the
    percentiles; the flight ring and export ring carry the records."""
    eng = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, steps_per_tick=2)
    rng = np.random.RandomState(0)
    reqs = [eng.add_request(_mk(rng, 10 + i, 6)) for i in range(3)]
    eng.run()
    assert all(r.done for r in reqs)

    assert metrics.get("serving.ttft_seconds").count() == 3
    assert metrics.get("serving.e2e_seconds").count() == 3
    assert metrics.get("serving.queue_wait_seconds").count() == 3
    # 6 tokens per request: 1 from prefill, 5 decode -> 5 TPOT samples
    assert metrics.get("serving.tpot_seconds").count() == 15

    st = eng.stats()
    lat = st["latency"]
    for key in ("ttft", "tpot", "e2e", "queue_wait"):
        assert set(lat[key]) == {"p50", "p90", "p99"}
        assert lat[key]["p50"] <= lat[key]["p99"]
    assert lat["ttft"]["p50"] > 0 and lat["e2e"]["p50"] > 0
    # e2e covers ttft for every request
    assert lat["e2e"]["p99"] >= lat["ttft"]["p50"]

    # per-request records: on the request object, in the export ring,
    # and as kind="request" events in the flight recorder ring
    recs = export.recent_requests()
    assert [r["rid"] for r in recs] == [r.rid for r in reqs]
    for req, rec in zip(reqs, recs):
        assert req.trace["outcome"] == "finished"
        assert rec["tokens_out"] == 6 and rec["ticks"] == 3
        assert rec["ttft_s"] >= rec["queue_wait_s"] >= 0
        assert rec["e2e_s"] >= rec["ttft_s"] > 0
        assert rec["prefill_s"] > 0 and rec["tpot_mean_s"] > 0
        json.dumps(rec)
    flight = [e for e in flight_recorder.default_recorder().events()
              if e["kind"] == "request"]
    assert {e["rid"] for e in flight} == {r.rid for r in reqs}


def test_queue_wait_under_forced_deferral(model):
    """A request deferred on a drained pool (pool_exhausted) accumulates
    its real wait into queue_wait; the pressure gauges see it queued."""
    # pool of 3 blocks: each request reserves 2 worst-case (1 prompt
    # block + 1 growth), so the second MUST wait for the first to
    # finish and free its blocks
    eng = ServingEngine(model, max_batch=2, max_context=64,
                        block_size=16, num_blocks=3)
    rng = np.random.RandomState(1)
    r1 = eng.add_request(_mk(rng, 10, 20))
    r2 = eng.add_request(_mk(rng, 10, 20))
    assert metrics.get("serving.queue_depth").value() == 2
    assert metrics.get("serving.waiting").value() == 2
    eng.step()       # admits r1 only; r2 deferred (pool exhausted)
    assert r2.slot is None
    assert metrics.get("serving.running").value() == 1
    assert metrics.get("serving.waiting").value() == 1
    assert metrics.get("serving.rejections").value(
        reason="pool_exhausted") == 1
    eng.run()
    assert r1.done and r2.done
    # r2 waited for r1's whole decode: queue waits differ by orders
    assert r2.trace["queue_wait_s"] > r1.trace["queue_wait_s"]
    assert r2.trace["queue_wait_s"] > 10 * max(r1.trace["queue_wait_s"],
                                               1e-6)
    st = eng.stats()
    assert st["queue_depth"] == 0 and st["running"] == 0
    assert metrics.get("serving.queue_depth").value() == 0


def test_slo_violation_counters(model):
    """Impossible SLOs (1 ns) make every request/token a violation;
    0-valued flags (the default) count nothing."""
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    rng = np.random.RandomState(2)
    eng.add_request(_mk(rng, 8, 4))
    eng.run()
    slo = metrics.get("serving.slo_violations")
    assert slo.value(metric="ttft") == 0 and slo.value(metric="tpot") == 0
    with flag_guard(serving_ttft_slo_ms=1e-6, serving_tpot_slo_ms=1e-6):
        eng.add_request(_mk(rng, 8, 4))
        eng.run()
    assert slo.value(metric="ttft") == 1
    assert slo.value(metric="tpot") == 3      # every decode token


def test_rejection_trace_records(model):
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    with pytest.raises(ValueError):
        eng.add_request(Request(np.arange(1, 60), max_new_tokens=30))
    recs = export.recent_requests()
    assert recs and recs[-1]["outcome"] == "rejected:over_context"


def test_tracing_off_does_zero_work(model):
    """Acceptance: tracing cost is exactly 0 with the metrics gate off —
    no timestamps stamped, no sketch samples, no trace records."""
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    rng = np.random.RandomState(3)
    paddle.set_flags({"enable_metrics": False})
    r = eng.add_request(_mk(rng, 8, 4))
    eng.run()
    paddle.set_flags({"enable_metrics": True})
    assert r.done
    assert r._t_enqueue is None and r._t_first is None
    assert r.trace is None
    assert export.recent_requests() == []
    assert metrics.get("serving.ttft_seconds").count() == 0


@pytest.mark.slow  # 12s measured: forces a shape-change recompile on a second engine; trace schema + ttft/tpot pins stay fast
def test_recompile_blame_names_the_changed_dim(model):
    """Same callable, changed shape: the compile tracker's recompile
    event names exactly what changed (the ISSUE 6 acceptance check)."""
    eng = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, steps_per_tick=4)
    rng = np.random.RandomState(4)
    # budget 6 = 1 prefill token + 4-step tick + a k=1 tail, so BOTH
    # tick variants compile
    eng.add_request(_mk(rng, 10, 6))     # pad bucket 16
    eng.run()
    ent = compile_tracker.get("serving.prefill")
    assert ent["compiles"] == 1 and ent["last_cause"] == "first compile"
    eng.add_request(_mk(rng, 20, 6))     # pad bucket 32: recompile
    eng.run()
    ent = compile_tracker.get("serving.prefill")
    assert ent["compiles"] == 2
    assert "L_pad" in ent["last_cause"]
    assert "16 -> 32" in ent["last_cause"]
    # the tick cache compiled the k=4 program and the k=1 tail; blame
    # names the tick-size change
    tick = compile_tracker.get("serving.tick")
    assert tick["compiles"] == 2
    assert "steps_per_tick" in tick["last_cause"]
    rep = compile_tracker.compile_report()
    assert rep["total_compiles"] >= 4
    assert any("L_pad: 16 -> 32" in e["cause"] for e in rep["recompiles"])
    # registry counters feed compile_seconds_total on /metrics
    assert metrics.get("compile.events").value(fn="serving.prefill") == 2
    assert metrics.get("compile.seconds_total").value(
        fn="serving.prefill") > 0
    json.dumps(rep)


def test_jit_recompile_blame_names_shape_change():
    """to_static captures report into the tracker too: a second
    signature for the same function blames the changed arg shape."""
    from paddle_tpu.jit import to_static

    @to_static
    def traced_fn(a):
        return a * 2 + 1

    traced_fn(paddle.to_tensor(np.ones((2, 3), np.float32)))
    traced_fn(paddle.to_tensor(np.ones((2, 3), np.float32)))  # cache hit
    ent = compile_tracker.get("traced_fn")
    assert ent["compiles"] == 1
    traced_fn(paddle.to_tensor(np.ones((4, 3), np.float32)))
    ent = compile_tracker.get("traced_fn")
    assert ent["compiles"] == 2
    assert "arg0.shape" in ent["last_cause"]
    assert "2 -> 4" in ent["last_cause"]


def test_engine_scrape_acceptance(model):
    """ISSUE 6 acceptance: with FLAGS_metrics_port set, a running
    ServingEngine answers GET /metrics in Prometheus text format with
    serving_ttft_seconds quantiles and compile_seconds_total."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    rng = np.random.RandomState(5)
    try:
        with flag_guard(metrics_port=port):
            eng.add_request(_mk(rng, 8, 4))
            eng.run()                     # starts the endpoint
        srv = obs_http.current()
        assert srv is not None and srv.port == port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert 'serving_ttft_seconds{quantile="0.5"}' in body
        assert 'serving_ttft_seconds{quantile="0.99"}' in body
        assert 'serving_tpot_seconds{quantile="0.99"}' in body
        assert "serving_ttft_seconds_count 1" in body
        assert 'compile_seconds_total{fn="serving.prefill"}' in body
        assert "serving_queue_depth 0" in body
        reqs = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/requests", timeout=10).read())
        assert reqs[-1]["outcome"] == "finished"
    finally:
        obs_http.stop()
