"""paddle.sparse — COO/CSR sparse tensors and ops.

Parity: `python/paddle/sparse/` (creation.py sparse_coo_tensor/
sparse_csr_tensor, unary/binary/matmul ops, nn conv/norm/pool layers) and
`paddle/phi/core/sparse_coo_tensor.h` / `sparse_csr_tensor.h` with the
kernel corpus `paddle/phi/kernels/sparse/`.

TPU-native: a sparse tensor is (host-known int indices, autograd-tracked
value Tensor); all value math rides the dense op registry (shared tape,
AMP, NaN hooks), spatial rulebooks are built host-side, and the
FLOP-carrying gathers/matmuls land on the MXU.  `jax.experimental.sparse`
BCOO is an interop view (`._bcoo`).
"""

from . import nn  # noqa: F401
from .binary import (add, divide, masked_matmul, matmul, multiply,  # noqa: F401
                     subtract)
from .creation import (SparseCooTensor, SparseCsrTensor,  # noqa: F401
                       sparse_coo_tensor, sparse_csr_tensor)
from .unary import (abs, asin, asinh, atan, atanh, cast,  # noqa: F401,A004
                    expm1, leaky_relu, log1p, neg, pow, relu, relu6, sin,
                    sinh, softmax, sqrt, square, tanh)

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "add", "subtract", "multiply", "divide",
           "matmul", "masked_matmul",
           "relu", "relu6", "leaky_relu", "softmax", "abs", "neg", "sin",
           "sinh", "asin", "asinh", "atan", "atanh", "expm1", "log1p",
           "tanh", "sqrt", "square", "pow", "cast", "nn"]
