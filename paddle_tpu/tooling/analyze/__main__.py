"""graft-lint CLI.

Default mode is the RATCHET: analyze, diff against the committed
baseline, print only findings beyond it, exit non-zero iff any exist.
That is what tier-1 (`tests/test_static_analysis.py`) and CI run; a
clean tree exits 0 even though the baseline carries audited findings.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from .core import (DEFAULT_BASELINE_PATH, analyze_paths, load_baseline,
                   new_findings, save_baseline)


def default_paths() -> list:
    """The package tree, the repo-level drivers, and the test tree
    (code rules R001-R009 skip ``test_*`` modules; the tier-1 budget
    rule R010 runs ONLY on them)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))          # .../paddle_tpu
    repo = os.path.dirname(pkg)
    paths = [pkg]
    for extra in ("bench.py", "__graft_entry__.py", "tests"):
        p = os.path.join(repo, extra)
        if os.path.exists(p):
            paths.append(p)
    return paths


def changed_paths(ref: str) -> list:
    """Python files differing from git ``ref`` (committed diff) plus
    untracked ones — the incremental ratchet surface.  Deleted files
    are skipped; any git failure is LOUD (RuntimeError), never an
    empty-and-green run.  Note: the cross-file rule R005 sees only the
    changed files here, so cycles spanning into unchanged modules need
    the full-tree run (tier-1 keeps it)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    repo = os.path.dirname(pkg)
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--", "*.py"],
            capture_output=True, text=True, cwd=repo, timeout=60)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard",
             "--", "*.py"],
            capture_output=True, text=True, cwd=repo, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"graft-lint --changed: git failed: {e}")
    if diff.returncode != 0:
        raise RuntimeError("graft-lint --changed: `git diff "
                           f"--name-only {ref}` failed: "
                           + diff.stderr.strip())
    if untracked.returncode != 0:
        raise RuntimeError("graft-lint --changed: `git ls-files "
                           "--others` failed: "
                           + untracked.stderr.strip())
    names = set(diff.stdout.split()) | set(untracked.stdout.split())
    out = []
    for name in sorted(names):
        p = os.path.join(repo, name)
        if os.path.exists(p) and p.endswith(".py"):
            out.append(p)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tooling.analyze",
        description="graft-lint: JAX/TPU-aware static analysis "
                    "(rules R001-R010, ratcheted baseline)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "paddle_tpu package + bench.py + tests/)")
    p.add_argument("--changed", metavar="REF", nargs="?", const="HEAD",
                   default=None,
                   help="lint only files differing from git REF "
                        "(default HEAD) plus untracked files — the "
                        "seconds-scale incremental gate; the full-tree "
                        "tier-1 run stays authoritative (R005 cycles "
                        "into unchanged files are invisible here)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE_PATH,
                   help="ratchet baseline path (default: the committed "
                        "tooling/analyze/baseline.json)")
    p.add_argument("--check-baseline", action="store_true",
                   help="explicit ratchet mode (the default behavior; "
                        "kept as a named flag for CI readability)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current findings "
                        "and exit 0")
    p.add_argument("--list", action="store_true",
                   help="print EVERY finding (ignores the baseline); "
                        "exit non-zero iff any findings")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text lines")
    args = p.parse_args(argv)

    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if args.changed is not None:
        if args.paths:
            print("graft-lint: --changed and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        if args.update_baseline:
            print("graft-lint: refusing --update-baseline from a "
                  "--changed subset (the baseline must cover the whole "
                  "tree)", file=sys.stderr)
            return 2
        try:
            paths = changed_paths(args.changed)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        if not paths:
            print(f"graft-lint: no Python files changed vs "
                  f"{args.changed}; nothing to lint")
            return 0
        root = os.path.dirname(pkg)
    else:
        paths = args.paths or default_paths()
        root = os.path.commonpath([os.path.abspath(p) for p in paths])
        if os.path.isfile(root):
            root = os.path.dirname(root)
        # repo-relative paths in findings/baseline: anchor at the repo
        # root (parent of the package) when analyzing the default tree
        if os.path.commonpath([root, pkg]) == pkg or root == pkg:
            root = os.path.dirname(pkg)

    rules = args.rules.split(",") if args.rules else None
    errors: list = []
    t0 = time.perf_counter()
    try:
        findings = analyze_paths(paths, root=root, rules=rules,
                                 collect_errors=errors)
    except (FileNotFoundError, ValueError) as e:
        # bad path / non-.py file / unknown rule id: loud exit, never a
        # vacuous green run
        print(str(e), file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0

    if args.update_baseline:
        # a rule- or path-filtered run sees only a SLICE of the
        # findings; writing it over the committed baseline would
        # silently drop every other rule's/file's grandfathered entries
        # and fail the next full ratchet.  (A custom --baseline is the
        # escape hatch for scoped/experimental baselines.)
        if rules is not None:
            print("graft-lint: refusing --update-baseline with --rules "
                  "(the baseline must cover ALL rules; rerun without "
                  "--rules)", file=sys.stderr)
            return 2
        if args.paths and args.baseline == DEFAULT_BASELINE_PATH:
            print("graft-lint: refusing --update-baseline of the "
                  "committed baseline from an explicit path subset; "
                  "rerun with no paths (full default tree) or pass a "
                  "custom --baseline", file=sys.stderr)
            return 2
        save_baseline(args.baseline, findings)
        print(f"graft-lint: baseline updated with {len(findings)} "
              f"finding(s) -> {args.baseline}")
        return 0

    if args.list:
        shown = findings
        verdict_new = findings
    else:
        baseline = load_baseline(args.baseline)
        shown = new_findings(findings, baseline)
        verdict_new = shown

    if args.json:
        print(json.dumps({
            "schema": "paddle_tpu.graft-lint/v1",
            "elapsed_s": round(elapsed, 3),
            "total_findings": len(findings),
            "new_findings": [f.to_json() for f in verdict_new],
            "parse_errors": errors,
        }, indent=1))
    else:
        for f in shown:
            print(f.format())
        for e in errors:
            print(f"graft-lint: parse error (skipped): {e}",
                  file=sys.stderr)
        mode = "total" if args.list else "new (beyond baseline)"
        print(f"graft-lint: {len(shown)} {mode} finding(s), "
              f"{len(findings)} total, {elapsed:.2f}s")
    return 1 if verdict_new else 0


if __name__ == "__main__":
    sys.exit(main())
