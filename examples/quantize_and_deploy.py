"""QAT -> jit.save -> int8 artifact -> Predictor with runtime mixed
precision: the full quantized-deployment loop."""
from _mesh import ensure_devices

ensure_devices(1)
import tempfile  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import jit  # noqa: E402
from paddle_tpu.inference import Config, convert_to_int8, create_predictor  # noqa: E402
from paddle_tpu.quantization import (QAT, FakeQuanterWithAbsMaxObserver,  # noqa: E402
                                     QuantConfig)
from paddle_tpu.static import InputSpec  # noqa: E402

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                           paddle.nn.Linear(16, 4))
qnet = QAT(QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                       weight=FakeQuanterWithAbsMaxObserver)).quantize(net)
x = paddle.to_tensor(np.random.RandomState(0).randn(16, 8).astype(np.float32))
y = paddle.to_tensor(np.random.RandomState(1).randn(16, 4).astype(np.float32))
opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=qnet.parameters())
qnet.train()
for i in range(10):
    loss = paddle.mean((qnet(x) - y) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()
print("QAT loss:", float(loss.numpy()))

with tempfile.TemporaryDirectory() as d:
    qnet.eval()
    jit.save(qnet, f"{d}/m", input_spec=[InputSpec([None, 8], "float32")])
    convert_to_int8(f"{d}/m", f"{d}/m_int8", black_list=["bias"])
    cfg = Config(f"{d}/m_int8")
    cfg.enable_mixed_precision("bfloat16")
    pred = create_predictor(cfg)
    out = pred.run([np.asarray(x._value)])[0]
    ref = np.asarray(qnet(x)._value)
    print("int8-served vs QAT max err:", float(np.abs(out - ref).max()))
