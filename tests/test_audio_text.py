"""paddle.audio + paddle.text.

Mirrors the reference's `test/legacy_test/test_audio_functions.py` (librosa
parity reduced to closed-form checks), `test_audio_logmel_feature.py`, and
`test_viterbi_decode_op.py` (dynamic-programming result vs brute force).
"""

import itertools
import os
import wave

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio, text


# ------------------------------------------------------------------- audio
def test_mel_scale_round_trip():
    freqs = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0], np.float32)
    for htk in (False, True):
        mel = audio.functional.hz_to_mel(freqs, htk=htk)
        back = audio.functional.mel_to_hz(mel, htk=htk)
        np.testing.assert_allclose(back, freqs, rtol=1e-4, atol=1e-2)
    assert audio.functional.hz_to_mel(1000.0, htk=True) == \
        pytest.approx(1000.0, rel=1e-3)


def test_fbank_matrix_properties():
    fb = audio.functional.compute_fbank_matrix(sr=16000, n_fft=512,
                                               n_mels=40)
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has some support; DC bin is (near) empty
    assert (fb.sum(axis=1) > 0).all()


def test_window_functions():
    for name in ("hann", "hamming", "blackman", "rect"):
        w = audio.functional.get_window(name, 64)
        assert w.shape == (64,)
        assert w.max() <= 1.0 + 1e-6
    with pytest.raises(ValueError):
        audio.functional.get_window("kaiser9000", 64)


def test_spectrogram_detects_tone():
    sr, n_fft = 8000, 256
    t = np.arange(sr, dtype=np.float32) / sr
    tone = np.sin(2 * np.pi * 1000.0 * t)  # 1 kHz
    spec = audio.features.Spectrogram(n_fft=n_fft, hop_length=128)(
        paddle.to_tensor(tone[None, :]))
    s = np.asarray(spec._value)[0]          # (freq, time)
    peak_bin = s.mean(axis=1).argmax()
    want_bin = round(1000.0 / (sr / n_fft))
    assert abs(int(peak_bin) - want_bin) <= 1


def test_logmel_and_mfcc_shapes():
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 8000).astype(np.float32))
    lm = audio.features.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32,
                                          f_min=0.0)(x)
    assert np.asarray(lm._value).shape[0:2] == (2, 32)
    mfcc = audio.features.MFCC(sr=8000, n_mfcc=13, n_mels=32, n_fft=256,
                               f_min=0.0)(x)
    assert np.asarray(mfcc._value).shape[0:2] == (2, 13)
    assert np.isfinite(np.asarray(mfcc._value)).all()


def test_wav_save_load_round_trip(tmp_path):
    sr = 8000
    t = np.arange(sr // 2, dtype=np.float32) / sr
    wav = 0.5 * np.sin(2 * np.pi * 440 * t)[None, :]
    path = str(tmp_path / "tone.wav")
    audio.save(path, paddle.to_tensor(wav), sr)
    meta = audio.info(path)
    assert meta.sample_rate == sr and meta.num_channels == 1
    loaded, sr2 = audio.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(np.asarray(loaded._value), wav, atol=1e-3)


# -------------------------------------------------------------------- text
def brute_force_viterbi(pot, trans_nn, start, stop):
    """Enumerate all tag paths (tiny N, T)."""
    T, N = pot.shape
    best, best_path = -np.inf, None
    for path in itertools.product(range(N), repeat=T):
        s = start[path[0]] + pot[0, path[0]]
        for t in range(1, T):
            s += trans_nn[path[t - 1], path[t]] + pot[t, path[t]]
        s += stop[path[-1]]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    pot = rng.randn(B, T, N).astype(np.float32)
    full = rng.randn(N + 2, N + 2).astype(np.float32)
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(full))
    s_np = np.asarray(scores._value)
    p_np = np.asarray(paths._value)
    for b in range(B):
        want_s, want_p = brute_force_viterbi(
            pot[b], full[:N, :N], full[N, :N], full[:N, N + 1])
        assert s_np[b] == pytest.approx(want_s, rel=1e-5)
        assert list(p_np[b]) == want_p


def test_viterbi_no_bos_eos_and_layer():
    rng = np.random.RandomState(1)
    B, T, N = 2, 4, 3
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    dec = text.ViterbiDecoder(paddle.to_tensor(trans),
                              include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot))
    z = np.zeros(N, np.float32)
    for b in range(2):
        want_s, want_p = brute_force_viterbi(pot[b], trans, z, z)
        assert float(np.asarray(scores._value)[b]) == \
            pytest.approx(want_s, rel=1e-5)
        assert list(np.asarray(paths._value)[b]) == want_p


def test_text_dataset_requires_local_file():
    with pytest.raises(FileNotFoundError):
        text.UCIHousing()


def test_uci_housing_from_local_file(tmp_path):
    rng = np.random.RandomState(0)
    table = rng.rand(50, 14).astype(np.float32)
    f = tmp_path / "housing.data"
    np.savetxt(f, table)
    train = text.UCIHousing(data_file=str(f), mode="train")
    test = text.UCIHousing(data_file=str(f), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
