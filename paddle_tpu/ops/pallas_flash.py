"""FlashAttention-2 as Pallas TPU kernels (forward + backward).

Role of the reference's CUDA flash attention
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu` + vendored
`third_party/flashattn`, and the fused path of
`fused_multi_transformer_op.cu`): attention computed blockwise in VMEM so
the [S, S] score matrix never materializes in HBM.  This version carries
the reference kernel's full feature set: key-padding masks (the varlen
API's effective semantics), cross/cached attention (Sq != Sk with
end-aligned causal), GQA (fewer kv heads than q heads, resolved by index
maps — repeated K/V never touch HBM), and in-kernel dropout (the CUDA
kernel's philox dropout; here the TPU PRNG reseeded per block so the
backward kernels regenerate identical bits instead of storing the mask).

Layout follows paddle's flash-attn API: q, k, v are [B, S, nh, hd].

Kernel structure (the canonical TPU pattern — the *last* grid dimension is
sequential on TPU, so the online-softmax state lives in VMEM scratch across
k-block steps):

* forward: grid (B*nh, Sq/BQ, Sk/BK); scratch (m, l, acc); causal blocks
  above the (end-aligned) diagonal are skipped (`pl.when`), the diagonal
  block is masked with `broadcasted_iota`.  Outputs out and the logsumexp
  rows (for bwd).
* backward dq: grid (B*nh, Sq/BQ, Sk/BK), accumulates dq over k blocks.
* backward dkv: grid (B*nh, Sk/BK, Sq/BQ), accumulates dk/dv over q blocks.
  Uses the FlashAttention-2 identity ds = p * (dp - D), D = rowsum(dO * O),
  so no second softmax pass is needed.  With GQA the kernels emit per-
  q-head dk/dv ([B, nh, Sk, hd]) which XLA reduces over the head group.

All matmuls run on the MXU with f32 accumulation (`preferred_element_type`);
bf16 inputs stay bf16 in HBM.  On non-TPU backends the same kernels run
under the Pallas interpreter (CPU CI), selected automatically.

Dropout applies to the normalized probabilities (standard attention
semantics): l accumulates undropped p, acc accumulates dropped p @ v.
Each (batch*head, q-block, k-block) seeds the PRNG as
(seed, bh, qi, ki) so all three kernels see the same keep mask.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled builds; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention", "flash_attention_fwd",
           "flash_attention_bwd", "supported"]

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _resolve_interpret(interpret, rate):
    """The generic pallas interpreter has no lowering for the TPU PRNG
    primitives; when this jax build ships the TPU-semantics interpreter
    (``pltpu.InterpretParams``), dropout kernels in interpret mode (CPU
    CI) run under it.  Older builds don't have it — those fall through
    to the generic interpreter and the kernels switch to the hash-based
    mask (see :func:`_dropout_keep`)."""
    if interpret is None:
        interpret = _interpret_default()
    if (interpret is True and rate > 0.0 and _HAS_PLTPU
            and hasattr(pltpu, "InterpretParams")):
        return pltpu.InterpretParams()
    return interpret


def _native_prng(interpret) -> bool:
    """True when the TPU PRNG primitives can run: native TPU, or the
    TPU-semantics interpreter.  ``interpret is True`` is the generic
    interpreter, which has no lowering for them."""
    return interpret is not True


def supported(q_shape, k_shape=None, dtype=None) -> bool:
    """Kernel applicability: seqs multiples of their blocks, MXU-friendly
    hd, q heads an integer multiple of kv heads."""
    if len(q_shape) != 4:
        return False
    _, Sq, nh, hd = q_shape
    if k_shape is not None:
        _, Sk, nkv, hd_k = k_shape
        if hd_k != hd or nkv == 0 or nh % nkv:
            return False
        bk = min(128, Sk)
        if Sk % bk or Sk % 8 or Sk < 8:
            return False
    bq = min(128, Sq)
    return Sq % bq == 0 and Sq % 8 == 0 and Sq >= 8 and hd in (64, 128, 256)


def _block_seed(seed, bh, qi, ki):
    """Mix block coordinates into ONE extra seed word (Mosaic's
    tpu.prng_set_seed_32 accepts at most two values).  Bit-packed so
    distinct blocks get distinct words for all practical grids
    (bh < 2^11, qi/ki < 2^10); int32 wraparound beyond that is a
    harmless (deterministic) collision."""
    return jnp.int32(seed) ^ (bh * jnp.int32(1 << 20)
                              + qi * jnp.int32(1 << 10) + ki)


def _hash_bits(shape, seed_word):
    """Per-element uint32 stream as a pure function of (seed word,
    element coordinates): coordinate-mixed lowbias32 finalizer.  No
    PRNG state, so it lowers everywhere the VPU ops do — the dropout
    fallback for the generic pallas interpreter, which has no lowering
    for ``pltpu.prng_random_bits``."""
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    sw = jax.lax.bitcast_convert_type(
        jnp.asarray(seed_word, jnp.int32), jnp.uint32)
    x = (rows * jnp.uint32(0x0001_0193)
         + cols + sw * jnp.uint32(0x9E37_79B9))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB_352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846C_A68B)
    x = x ^ (x >> 16)
    return x


def _dropout_keep(shape, rate, seed_word, native_prng):
    """Regenerate the dropout keep-mask for the current block.  Both
    paths are pure functions of (seed_word, coords), so forward and
    backward kernels redraw bit-identical masks.  ``native_prng``
    selects the hardware PRNG (TPU / TPU-semantics interpreter) vs the
    hash stream (generic interpreter)."""
    if native_prng:
        pltpu.prng_seed(seed_word)
        bits = pltpu.prng_random_bits(shape)
        # bitcast keeps the threshold comparison unsigned
        if bits.dtype != jnp.uint32:
            bits = jax.lax.bitcast_convert_type(bits, jnp.uint32)
    else:
        bits = _hash_bits(shape, seed_word)
    # keep with probability (1 - rate): threshold on the uint32 line
    thresh = jnp.uint32((1.0 - rate) * 4294967295.0)
    return bits < thresh


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, mask_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, bq, bk, nk, offset, rate, has_mask,
                native_prng):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * bq
    k_start = ki * bk

    # causal (end-aligned: query i attends keys <= i + offset, offset =
    # Sk - Sq): skip blocks strictly above the shifted diagonal
    run = True if not causal else (k_start <= q_start + offset + bq - 1)

    @pl.when(run)
    def _():
        q = q_ref[:, :]                       # [bq, hd]
        k = k_ref[:, :]                       # [bk, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        valid2d = None
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k_start
            valid2d = rows + offset >= cols
        if has_mask:
            valid = mask_ref[0, :] != 0                   # [bk]
            vk = jnp.broadcast_to(valid[None, :], (bq, bk))
            valid2d = vk if valid2d is None else (valid2d & vk)
        if valid2d is not None:
            s = jnp.where(valid2d, s, _NEG_INF)
        m_prev = m_scr[:, 0]                         # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])              # [bq, bk]
        if has_mask or (causal and offset < 0):
            # a fully-masked row in this block has m_new == s == _NEG_INF,
            # making exp(s - m_new) = 1 on masked entries — zero explicitly.
            # Only a kv mask or a negative causal offset can fully mask a
            # row (offset >= 0 keeps at least key 0 valid for every query);
            # plain causal self-attention skips this VPU pass.
            p = jnp.where(valid2d, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)              # [bq]
        l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
        v = v_ref[:, :]                        # [bk, hd]
        if rate > 0.0:
            keep = _dropout_keep((bq, bk), rate,
                                 _block_seed(seed_ref[0], bh, qi, ki),
                                 native_prng)
            p_v = jnp.where(keep, p / (1.0 - rate), 0.0)
        else:
            p_v = p
        pv = jax.lax.dot_general(
            p_v.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, hd]
        acc_scr[:] = acc_scr[:] * alpha[:, None] + pv
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:, :] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        # lse rows broadcast across a 128-lane dim (Mosaic tile alignment,
        # same layout as jax's reference flash kernel)
        lse_ref[:, :] = m_scr[:, :] + jnp.broadcast_to(
            jnp.log(l_safe)[:, None], lse_ref.shape)


def _bnsh(x):
    return jnp.transpose(x, (0, 2, 1, 3))  # [B, S, nh, hd] -> [B, nh, S, hd]


def _pick_block(S, target):
    """Largest block <= target that divides S (halving; terminates at <=128
    because `supported` requires S % min(128, S) == 0)."""
    b = min(target, S)
    while S % b:
        b //= 2
    return b


def _seed_arr(seed):
    if seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(seed, jnp.int32).reshape((1,))


def _mask_arr(kv_mask, B, Sk):
    """[B, Sk] (or broadcastable) 0/1 key-validity -> [B, 1, Sk] int32."""
    if kv_mask is None:
        return jnp.ones((B, 1, Sk), jnp.int32)
    m = jnp.asarray(kv_mask)
    m = jnp.broadcast_to(m.reshape(m.shape[0], 1, m.shape[-1]), (B, 1, Sk))
    return m.astype(jnp.int32)


def flash_attention_fwd(q, k, v, causal=False, interpret=None,
                        kv_mask=None, dropout_rate=0.0, seed=None,
                        block_q=512, block_k=1024):
    """Returns (out, lse); out [B, Sq, nh, hd], lse [B, nh, Sq, 128]
    (float32, rows broadcast across the 128-lane dim).

    k, v may carry fewer heads than q (GQA): nh % nkv == 0; the kernel
    resolves the head group through the k/v index maps, so the repeated
    heads never materialize.  kv_mask is a [B, Sk] 0/1 key-validity mask
    (padding); dropout_rate with `seed` (int32) applies in-kernel dropout
    to the normalized probabilities.

    Kernels run in BNSH layout so blocks are rank-2 [block, hd] after
    squeezing the (batch, head) dims — Mosaic's lane/sublane alignment
    applies to the (seq, hd) dims, which are tile-friendly."""
    interpret = _resolve_interpret(interpret, float(dropout_rate))
    B, Sq, nh, hd = q.shape
    Sk, nkv = k.shape[1], k.shape[2]
    group = nh // nkv
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    nq, nk = Sq // bq, Sk // bk
    scale = 1.0 / math.sqrt(hd)
    rate = float(dropout_rate)
    has_mask = kv_mask is not None

    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, nk=nk, offset=Sk - Sq,
                             rate=rate, has_mask=has_mask,
                             native_prng=_native_prng(interpret))
    grid = (B * nh, nq, nk)

    def qmap(bh, qi, ki, *_):
        return (bh // nh, bh % nh, qi, 0)

    def kmap(bh, qi, ki, *_):
        return (bh // nh, (bh % nh) // group, ki, 0)

    def mmap(bh, qi, ki, *_):
        return (bh // nh, 0, ki)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, bq, hd), qmap),
            pl.BlockSpec((None, None, bk, hd), kmap),
            pl.BlockSpec((None, None, bk, hd), kmap),
            pl.BlockSpec((None, 1, bk), mmap),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bq, hd), qmap),
            pl.BlockSpec((None, None, bq, 128), qmap),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, hd), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, nh, Sq, hd), q.dtype),
            jax.ShapeDtypeStruct((B, nh, Sq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(_seed_arr(seed), _bnsh(q), _bnsh(k), _bnsh(v), _mask_arr(kv_mask, B, Sk))
    return jnp.transpose(out, (0, 2, 1, 3)), lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   mask_ref, dq_ref, dq_scr,
                   *, scale, causal, bq, bk, nk, offset, rate, has_mask,
                   native_prng):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * bq
    k_start = ki * bk
    run = True if not causal else (k_start <= q_start + offset + bq - 1)

    @pl.when(run)
    def _():
        q = q_ref[:, :]
        k = k_ref[:, :]
        v = v_ref[:, :]
        do = do_ref[:, :].astype(jnp.float32)
        lse = lse_ref[:, 0:1]                  # [bq, 1]
        # D = rowsum(dO * O) (FlashAttention-2), computed on the block
        delta = jnp.sum(do * o_ref[:, :].astype(jnp.float32), axis=1,
                        keepdims=True)         # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid2d = None
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k_start
            valid2d = rows + offset >= cols
        if has_mask:
            valid = mask_ref[0, :] != 0
            vk = jnp.broadcast_to(valid[None, :], (bq, bk))
            valid2d = vk if valid2d is None else (valid2d & vk)
        if valid2d is not None:
            s = jnp.where(valid2d, s, _NEG_INF)
        p = jnp.exp(s - lse)                         # [bq, bk]
        if has_mask or (causal and offset < 0):
            # fully-masked rows carry lse = _NEG_INF; zero explicitly
            # (plain causal offset>=0 rows always keep key 0 — skip)
            p = jnp.where(valid2d, p, 0.0)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, bk]
        if rate > 0.0:
            keep = _dropout_keep((bq, bk), rate,
                                 _block_seed(seed_ref[0], bh, qi, ki),
                                 native_prng)
            dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[:, :] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    mask_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, bq, bk, nq, offset, rate, has_mask,
                    native_prng):
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * bq
    k_start = ki * bk
    run = True if not causal else (k_start <= q_start + offset + bq - 1)

    @pl.when(run)
    def _():
        q = q_ref[:, :]
        k = k_ref[:, :]
        v = v_ref[:, :]
        do = do_ref[:, :].astype(jnp.float32)
        lse = lse_ref[:, 0:1]                  # [bq, 1]
        delta = jnp.sum(do * o_ref[:, :].astype(jnp.float32), axis=1,
                        keepdims=True)         # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid2d = None
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + k_start
            valid2d = rows + offset >= cols
        if has_mask:
            valid = mask_ref[0, :] != 0
            vk = jnp.broadcast_to(valid[None, :], (bq, bk))
            valid2d = vk if valid2d is None else (valid2d & vk)
        if valid2d is not None:
            s = jnp.where(valid2d, s, _NEG_INF)
        p = jnp.exp(s - lse)                         # [bq, bk]
        if has_mask or (causal and offset < 0):
            # fully-masked rows carry lse = _NEG_INF; zero explicitly
            # (plain causal offset>=0 rows always keep key 0 — skip)
            p = jnp.where(valid2d, p, 0.0)
        if rate > 0.0:
            # seeded by LOGICAL block coords (bh, qi, ki) — this kernel's
            # grid iterates (bh, ki, qi) but must regenerate the exact
            # bits the forward drew for the (qi, ki) tile
            keep = _dropout_keep((bq, bk), rate,
                                 _block_seed(seed_ref[0], bh, qi, ki),
                                 native_prng)
            p_v = jnp.where(keep, p / (1.0 - rate), 0.0)
        else:
            keep = None
            p_v = p
        # dv += (dropped p)^T @ do
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p_v, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bk, hd]
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, bk]
        if rate > 0.0:
            dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
        ds = p * (dp - delta) * scale                # [bq, bk]
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[:, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:, :] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(causal, interpret, kv_mask_shape, rate, res, g,
               block_q=512, block_k=512):
    q, k, v, out, lse, mask_arr, seed_arr = res
    interpret = _resolve_interpret(interpret, rate)
    B, Sq, nh, hd = q.shape
    Sk, nkv = k.shape[1], k.shape[2]
    group = nh // nkv
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    nq, nk = Sq // bq, Sk // bk
    scale = 1.0 / math.sqrt(hd)
    # kv_mask_shape records whether the FORWARD had a user mask; when it
    # didn't, the saved residual mask is the internally-built all-ones
    # array (never user data), so applying it would be the identity — the
    # unmasked train path skips the mask reads and both extra VPU
    # `where` passes entirely (round-3 applied it unconditionally, which
    # cost ~9% of the GPT-124M train step)
    has_mask = kv_mask_shape is not None

    qb, kb, vb = _bnsh(q), _bnsh(k), _bnsh(v)
    ob, gb = _bnsh(out), _bnsh(g)

    def qmap(bh, qi, ki, *_):
        return (bh // nh, bh % nh, qi, 0)

    def kmap(bh, qi, ki, *_):
        return (bh // nh, (bh % nh) // group, ki, 0)

    def mmap(bh, qi, ki, *_):
        return (bh // nh, 0, ki)

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * nh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, None, bq, hd), qmap),
            pl.BlockSpec((None, None, bk, hd), kmap),
            pl.BlockSpec((None, None, bk, hd), kmap),
            pl.BlockSpec((None, None, bq, hd), qmap),
            pl.BlockSpec((None, None, bq, hd), qmap),
            pl.BlockSpec((None, None, bq, 128), qmap),
            pl.BlockSpec((None, 1, bk), mmap),
        ],
        out_specs=pl.BlockSpec((None, None, bq, hd), qmap),
        scratch_shapes=[pltpu.VMEM((bq, hd), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, offset=Sk - Sq, rate=rate,
                          has_mask=has_mask,
                          native_prng=_native_prng(interpret)),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, Sq, hd), q.dtype),
        interpret=interpret,
    )(seed_arr, qb, kb, vb, ob, gb, lse, mask_arr)

    # dkv: grid ordered (bh, ki, qi) — q is the sequential axis
    def kmap2(bh, ki, qi, *_):
        return (bh // nh, (bh % nh) // group, ki, 0)

    def kout2(bh, ki, qi, *_):
        return (bh // nh, bh % nh, ki, 0)

    def qmap2(bh, ki, qi, *_):
        return (bh // nh, bh % nh, qi, 0)

    def mmap2(bh, ki, qi, *_):
        return (bh // nh, 0, ki)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * nh, nk, nq),
        in_specs=[
            pl.BlockSpec((None, None, bq, hd), qmap2),
            pl.BlockSpec((None, None, bk, hd), kmap2),
            pl.BlockSpec((None, None, bk, hd), kmap2),
            pl.BlockSpec((None, None, bq, hd), qmap2),
            pl.BlockSpec((None, None, bq, hd), qmap2),
            pl.BlockSpec((None, None, bq, 128), qmap2),
            pl.BlockSpec((None, 1, bk), mmap2),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bk, hd), kout2),
            pl.BlockSpec((None, None, bk, hd), kout2),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, hd), jnp.float32),
            pltpu.VMEM((bk, hd), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, offset=Sk - Sq, rate=rate,
                          has_mask=has_mask,
                          native_prng=_native_prng(interpret)),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, nh, Sk, hd), k.dtype),
            jax.ShapeDtypeStruct((B, nh, Sk, hd), v.dtype),
        ],
        interpret=interpret,
    )(seed_arr, qb, kb, vb, ob, gb, lse, mask_arr)
    if group > 1:
        # GQA: reduce per-q-head grads over each kv head's group
        dk = dk.reshape(B, nkv, group, Sk, hd).sum(axis=2, dtype=jnp.float32)
        dv = dv.reshape(B, nkv, group, Sk, hd).sum(axis=2, dtype=jnp.float32)
        dk = dk.astype(k.dtype)
        dv = dv.astype(v.dtype)
    tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv), None, None


def flash_attention_bwd(q, k, v, out, lse, g, causal=False, interpret=None):
    """Public backward entry point: gradients (dq, dk, dv) of
    `flash_attention_fwd`'s output w.r.t. q/k/v, given the forward's
    residuals.  `lse` is the [B, nh, Sq, 128] lane-broadcast logsumexp the
    forward returns (callers holding [B, nh, Sq] rows may broadcast them —
    only lane 0 is read).  The FA2 identities hold for any *global*
    normalizer, so chunked/ring callers may pass a combined lse to get this
    chunk's contribution to the global gradients."""
    B, Sk = k.shape[0], k.shape[1]
    dq, dk, dv, _, _ = _flash_bwd(
        causal, interpret, None, 0.0,
        (q, k, v, out, lse, _mask_arr(None, B, Sk), _seed_arr(None)), g)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 7, 8))
def _flash_attention_core(q, k, v, causal, interpret,
                          kv_mask, seed, kv_mask_shape, dropout_rate):
    out, _ = flash_attention_fwd(q, k, v, causal, interpret,
                                 kv_mask, dropout_rate, seed)
    return out


def flash_attention(q, k, v, causal=False, interpret=None,
                    kv_mask=None, seed=None, kv_mask_shape=None,
                    dropout_rate=0.0):
    """Flash attention; q [B, Sq, nh, hd], k/v [B, Sk, nkv, hd] ->
    [B, Sq, nh, hd].  kv_mask: optional [B, Sk] 0/1 key-validity;
    seed: optional int32 scalar for dropout.  `kv_mask_shape` is the
    static mirror of kv_mask's presence (custom_vjp nondiff args must be
    static); it is derived here so a direct caller can never get a
    masked forward with an unmasked backward."""
    if kv_mask is not None and kv_mask_shape is None:
        kv_mask_shape = tuple(kv_mask.shape)
    return _flash_attention_core(q, k, v, causal, interpret, kv_mask,
                                 seed, kv_mask_shape, dropout_rate)


def _fa_fwd(q, k, v, causal, interpret, kv_mask, seed, kv_mask_shape,
            dropout_rate):
    out, lse = flash_attention_fwd(q, k, v, causal, interpret,
                                   kv_mask, dropout_rate, seed)
    B, Sk = k.shape[0], k.shape[1]
    return out, (q, k, v, out, lse, _mask_arr(kv_mask, B, Sk),
                 _seed_arr(seed))


def _fa_bwd(causal, interpret, kv_mask_shape, dropout_rate, res, g):
    return _flash_bwd(causal, interpret, kv_mask_shape, dropout_rate,
                      res, g)


_flash_attention_core.defvjp(_fa_fwd, _fa_bwd)
