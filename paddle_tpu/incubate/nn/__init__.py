from . import functional  # noqa: F401
