"""High-level Model API: prepare / fit / evaluate / predict / save / load.

Parity: `python/paddle/hapi/model.py` — Model (`:1052`), train_batch
(`:1194`), eval_batch (`:1251`), predict_batch (`:1307`), save (`:1356`),
load (`:1423`), prepare (`:1670`), fit (`:1750`), evaluate (`:1999`),
predict (`:2110`), summary (`:2376`).

TPU-native: the reference splits into Dynamic/StaticGraphAdapter; here there
is one path — the train/eval steps are captured by `paddle_tpu.jit.to_static`
into a single donated XLA program per mode (prepare(jit_compile=True), the
default), with metrics computed on the step outputs outside the graph.  Set
jit_compile=False for pure eager debugging.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .. import flags as _flags
from .. import io as paddle_io
from ..framework import io as framework_io
from ..framework.tensor import Tensor
from ..metric import Metric
from ..nn.layer.layers import Layer
from ..observability import flight_recorder as _flight
from ..observability import metrics as _obs_metrics
from ..observability import telemetry as _telemetry
from .callbacks import config_callbacks

_M_STEP_S = _obs_metrics.histogram(
    "train.step_seconds",
    "host wall time to dispatch one train step (labels: mode); on "
    "async accelerators this is enqueue time unless the caller syncs "
    "inside the step — the first sample includes XLA compile")
_M_LOSS_SYNC = _obs_metrics.counter(
    "train.loss_syncs",
    "host materializations of the hapi train loss; with "
    "FLAGS_loss_sync_interval=K, fit performs ceil(steps/K) of these")


def _batch_tokens(inputs) -> int:
    """Telemetry token heuristic: 2-D integer batches are [B, S] token
    ids and count B*S; anything else (images, dense features) counts
    batch rows."""
    if not inputs:
        return 0
    x = inputs[0]
    shape = getattr(x, "shape", None) or ()
    if not shape:
        return 1
    try:
        is_ids = len(shape) == 2 and np.dtype(x.dtype).kind in "iu"
    except Exception:  # noqa: BLE001 - exotic dtype: fall back to rows
        is_ids = False
    return int(shape[0]) * int(shape[1]) if is_ids else int(shape[0])

__all__ = ["Model"]


def to_list(value):
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    """An trainable/inferable instance wrapping a `Layer`."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit_compile = True
        self._compiled = {}
        self.stop_training = False
        self._save_dir = None
        self.mode = "train"
        self._pending_accum = False
        self._train_steps = 0       # paces the flag-spaced loss sync
        self._last_synced_step = -1  # for flushed_steps attribution

    # ------------------------------------------------------------------ prep
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile: bool = True):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be a paddle.metric.Metric, "
                                f"got {type(m).__name__}")
        self._jit_compile = jit_compile
        self._compiled = {}
        self._amp_kwargs = None
        self._scaler = None
        if amp_configs is not None:
            # reference hapi accepts "O1"/"O2" or a dict mixing auto_cast
            # and GradScaler settings (`hapi/model.py` _check_amp_configs)
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            cfg = dict(amp_configs)
            level = cfg.pop("level", "O1")
            if level not in ("O0", "O1", "O2"):
                raise ValueError(f"amp level must be O0/O1/O2, got {level}")
            if level != "O0":
                from .. import amp as _amp
                scaler_keys = {k: cfg.pop(k) for k in list(cfg)
                               if k in ("init_loss_scaling", "incr_ratio",
                                        "decr_ratio", "incr_every_n_steps",
                                        "decr_every_n_nan_or_inf",
                                        "use_dynamic_loss_scaling")}
                self._amp_kwargs = {"level": level, **cfg}
                # bf16 on TPU needs no loss scaling; fp16 (any alias) does
                import jax.numpy as _jnp

                from ..core import dtypes as _dtypes
                is_fp16 = "dtype" in cfg and _dtypes.convert_dtype(
                    cfg["dtype"]) == _jnp.dtype(_jnp.float16)
                if scaler_keys or is_fp16:
                    self._scaler = _amp.GradScaler(**scaler_keys)

    # ----------------------------------------------------------------- steps
    def _mode_fn(self, mode):
        """The raw (uncompiled) step function for `mode`."""
        import contextlib

        def _amp_ctx():
            if self._amp_kwargs is None:
                return contextlib.nullcontext()
            from .. import amp as _amp
            return _amp.auto_cast(True, **self._amp_kwargs)

        if mode == "train":
            def step(*args):
                n_in = self._n_inputs
                ins, labs = args[:n_in], args[n_in:]
                with _amp_ctx():
                    outputs = to_list(self.network(*ins))
                    loss = self._loss(*(outputs + list(labs)))
                if self._scaler is not None:
                    self._scaler.scale(loss).backward()
                    self._scaler.step(self._optimizer)
                else:
                    loss.backward()
                    self._optimizer.step()
                self._optimizer.clear_grad()
                return [loss] + outputs
        elif mode == "accumulate":  # train_batch(update=False)
            def step(*args):
                n_in = self._n_inputs
                ins, labs = args[:n_in], args[n_in:]
                with _amp_ctx():
                    outputs = to_list(self.network(*ins))
                    loss = self._loss(*(outputs + list(labs)))
                if self._scaler is not None:
                    self._scaler.scale(loss).backward()
                else:
                    loss.backward()
                return [loss] + outputs
        elif mode == "eval":
            def step(*args):
                n_in = self._n_inputs
                ins, labs = args[:n_in], args[n_in:]
                outputs = to_list(self.network(*ins))
                res = list(outputs)
                if self._loss is not None:
                    res = [self._loss(*(outputs + list(labs)))] + res
                return res
        else:
            def step(*args):
                return to_list(self.network(*args))
        return step

    def _run_step(self, mode, inputs, labels):
        inputs = [_as_tensor(x) for x in to_list(inputs)]
        labels = [_as_tensor(y) for y in to_list(labels)]
        self._n_inputs = len(inputs)
        if mode in ("train", "accumulate"):
            self.network.train()
        else:
            self.network.eval()
        key = (mode, len(inputs), len(labels))
        # grad accumulation mutates .grad across calls, which lives outside
        # the captured program state — run it (and the step consuming it)
        # eagerly; steady-state update=True training stays compiled
        eager_needed = mode == "accumulate" or \
            (mode == "train" and self._pending_accum) or \
            (mode in ("train", "accumulate") and self._scaler is not None)
        # (dynamic loss scaling branches on found_inf on the host, which a
        # captured program can't; bf16 AMP without a scaler stays compiled)
        if self._jit_compile and not eager_needed:
            if key not in self._compiled:
                from ..jit import to_static
                self._compiled[key] = to_static(self._mode_fn(mode),
                                                full_graph=True)
            fn = self._compiled[key]
        else:
            fn = self._mode_fn(mode)
        if mode in ("train", "accumulate"):
            self._pending_accum = mode == "accumulate"
        import time
        t0 = time.perf_counter()
        # the guard turns an unhandled train-step exception into a
        # flight-recorder dump (watchdog flag on) before it propagates
        with _flight.guard(f"hapi.{mode}_step"):
            out = fn(*(inputs + labels))
        _M_STEP_S.observe(time.perf_counter() - t0, mode=mode)
        return out, labels

    def train_batch(self, inputs, labels=None, update=True):
        """One optimizer step (update=False: accumulate grads only);
        returns (loss, [metric results]).  The loss is a numpy array on
        synced steps; with FLAGS_loss_sync_interval=K every other step
        leaves it as the raw device array (no host round trip — the step
        dispatch returns while the device still computes)."""
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer=..., loss=...) first")
        interval = max(int(_flags.get_flag("loss_sync_interval")), 1)
        step_idx = self._train_steps
        sync = step_idx % interval == 0
        self._train_steps += 1
        # the telemetry bracket spans dispatch AND (on synced steps) the
        # loss host read, so a synced record's wall_s is completed-step
        # time even on async backends; unsynced records are enqueue time
        # and stay marked synced=False
        st = _telemetry.default_timeline().step(
            tokens=_batch_tokens(to_list(inputs)),
            mode="train" if update else "accumulate")
        with st:
            res, labs = self._run_step("train" if update else "accumulate",
                                       inputs, labels)
            loss = res[0]
            if sync:
                loss_np = np.asarray(loss._value)
                _M_LOSS_SYNC.inc()
                st.annotate(loss=float(loss_np.reshape(-1)[0]), synced=True)
                flushed = step_idx - self._last_synced_step
                self._last_synced_step = step_idx
                if flushed > 1:
                    # the synced wall includes the drained dispatch queue
                    # of the flushed-1 unsynced steps before it
                    st.annotate(flushed_steps=flushed)
                if self._scaler is not None:
                    # the flag-spaced found_inf/scale read-back
                    self._scaler._sync_fused_state()
        outputs = res[1:]
        metrics = self._update_metrics(outputs, labs)
        if not sync:
            return loss._value, metrics
        # NaN/Inf watchdog probe — gated ONLY by its own flag, so it
        # fires even with the metrics registry (and the timeline) off;
        # probes ride the synced steps only
        _flight.check_finite(float(loss_np.reshape(-1)[0]),
                             site="hapi.train.loss",
                             step=st.index if st.index >= 0 else None)
        return loss_np, metrics

    def eval_batch(self, inputs, labels=None):
        res, labs = self._run_step("eval", inputs, labels)
        if self._loss is not None:
            loss, outputs = res[0], res[1:]
            metrics = self._update_metrics(outputs, labs)
            return np.asarray(loss._value), metrics
        return None, self._update_metrics(res, labs)

    def predict_batch(self, inputs):
        res, _ = self._run_step("predict", inputs, [])
        return [np.asarray(o._value) for o in res]

    def _update_metrics(self, outputs, labels):
        results = []
        for m in self._metrics:
            computed = m.compute(*(list(outputs) + list(labels)))
            results.append(m.update(*to_list(computed)))
        return results

    # ------------------------------------------------------------- save/load
    def _remap_opt_state(self, sd, to_structured: bool):
        return self._optimizer.remap_state_keys(self.network, sd,
                                                to_structured)

    def save(self, path: str, training: bool = True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(
                self._remap_opt_state(self._optimizer.state_dict(), True),
                path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        params = framework_io.load(path + ".pdparams")
        if skip_mismatch:
            own = self.network.state_dict()
            params = {k: v for k, v in params.items()
                      if k in own and tuple(np.asarray(
                          v._value if isinstance(v, Tensor) else v).shape)
                      == tuple(own[k].shape)}
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(
                self._remap_opt_state(framework_io.load(opt_path), False))
        self._compiled = {}  # new weights invalidate donated buffers

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    # -------------------------------------------------- fault-tolerant state
    def _train_state(self, epoch: int, step_in_epoch: int) -> dict:
        """Everything a resumed process needs for a bit-identical
        continuation: params, optimizer accumulators (structured keys),
        the fused-path device scalars materialized through the state_dict
        sync points (`optimizer._global_step`, GradScaler
        scale/good/bad), framework + numpy RNG, and the loop position."""
        from ..framework import random as _random
        meta = {
            "epoch": int(epoch), "step_in_epoch": int(step_in_epoch),
            "train_steps": int(self._train_steps),
            "last_synced_step": int(self._last_synced_step),
            "scaler": self._scaler.state_dict()
            if self._scaler is not None else None,
            "rng": {"framework": _random.rng_checkpoint_state(),
                    "numpy": np.random.get_state(),
                    "numpy_epoch_start": getattr(self, "_epoch_np_state",
                                                 None)},
        }
        return {"model": self.network.state_dict(),
                "optimizer": self._remap_opt_state(
                    self._optimizer.state_dict(), True),
                "meta": meta}

    def _restore_train_state(self, manager, step=None):
        """Load the newest complete version (or `step`) from `manager`
        and restore model/optimizer/scaler/RNG + loop counters.  Returns
        the restored meta dict, or None when the root holds no complete
        checkpoint yet (auto-resume on a first launch starts fresh)."""
        if self._optimizer is None:
            raise RuntimeError("call prepare(optimizer=..., loss=...) "
                               "before fit(resume=...)")
        if step is None:
            step = manager.latest_complete()
            if step is None:
                return None
        state = manager.load(step)
        self.network.set_state_dict(
            {k: v if isinstance(v, Tensor) else Tensor(np.asarray(v))
             for k, v in state["model"].items()})
        self._optimizer.set_state_dict(
            self._remap_opt_state(state["optimizer"], False))
        meta = state.get("meta", {})
        if self._scaler is not None and meta.get("scaler"):
            self._scaler.load_state_dict(meta["scaler"])
        rng = meta.get("rng") or {}
        if rng.get("framework") is not None:
            from ..framework import random as _random
            _random.restore_rng_checkpoint_state(rng["framework"])
        self._train_steps = int(meta.get("train_steps", 0))
        self._last_synced_step = int(meta.get("last_synced_step", -1))
        self._compiled = {}  # new weights invalidate donated buffers
        return meta

    # ------------------------------------------------------------------- fit
    def _make_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, paddle_io.DataLoader):
            return data
        return paddle_io.DataLoader(data, batch_size=batch_size,
                                    shuffle=shuffle, drop_last=drop_last,
                                    num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            checkpoint=None, resume=False):
        """Train; with ``checkpoint`` (a `CheckpointManager` or a root
        path) fit takes atomic versioned checkpoints every
        ``save_interval`` optimizer steps and handles SIGTERM/SIGINT by
        finishing the in-flight step, taking an emergency checkpoint and
        returning cleanly.  ``resume=True`` restores the newest complete
        version (params, optimizer + scaler state, RNG, epoch/step
        position) before training; ``resume=<step>`` picks a version.
        An empty checkpoint root with resume=True starts fresh, so the
        same launch command works before and after a preemption."""
        assert train_data is not None, "train_data must be given"
        from ..observability import http as _obs_http
        _obs_http.start_from_flags()   # /metrics endpoint, flag-gated
        # restart the loss-sync phase: each fit performs exactly
        # ceil(steps/K) host reads and step 0 always syncs (so logs
        # carry a 'loss' from the first callback on)
        self._train_steps = 0
        self._last_synced_step = -1
        manager = checkpoint
        if isinstance(checkpoint, (str, os.PathLike)):
            from ..distributed.checkpoint import CheckpointManager
            manager = CheckpointManager(str(checkpoint))
        start_epoch, skip_steps, resume_rng = 0, 0, None
        if resume:
            if manager is None:
                raise ValueError("fit(resume=...) requires checkpoint=...")
            meta = self._restore_train_state(
                manager, None if resume is True else int(resume))
            if meta is not None:
                start_epoch = int(meta.get("epoch", 0))
                skip_steps = int(meta.get("step_in_epoch", -1)) + 1
                resume_rng = (meta.get("rng") or {})
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         drop_last, num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False, False,
                                        num_workers)
        self._save_dir = save_dir
        steps = len(train_loader) if hasattr(train_loader, "__len__") else None
        if skip_steps and steps is not None and skip_steps >= steps:
            # the checkpoint landed on an epoch boundary: resume at the
            # top of the next epoch instead of replaying an empty tail.
            # The SAVE-TIME numpy state must still be restored HERE —
            # mid-epoch resume restores it after the skip completes, but
            # with no steps to skip that code never runs, and the next
            # epoch's shuffle permutation would be drawn from an
            # unrelated stream (the divergence the SIGTERM-at-epoch-end
            # resume test used to flake on)
            start_epoch += 1
            if resume_rng.get("numpy") is not None:
                np.random.set_state(resume_rng["numpy"])
            skip_steps, resume_rng = 0, None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir,
            metrics=self._metrics_name())

        self.stop_training = False
        if manager is not None:
            from ..distributed.checkpoint import manager as _ckpt_mgr
            _ckpt_mgr.clear_preemption()
            manager.install_signal_handlers()
        logs = {}
        try:
            cbks.on_train_begin({})
            for epoch in range(start_epoch, epochs):
                cbks.on_epoch_begin(epoch, {})
                first = epoch == start_epoch
                logs = self._run_one_epoch(
                    train_loader, cbks, "train", epoch=epoch, ckpt=manager,
                    skip_steps=skip_steps if first else 0,
                    resume_rng=resume_rng if first else None)
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and epoch % eval_freq == 0 \
                        and not self.stop_training:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              _callbacks=cbks)
                    cbks.on_eval_end(eval_logs)
                if self.stop_training:
                    break
            cbks.on_train_end(logs)
        finally:
            if manager is not None:
                manager.uninstall_signal_handlers()
        if manager is not None:
            manager.wait()  # surface a failed trailing async save
        return logs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            names.extend(to_list(m.name()))
        return names

    def _split_batch(self, batch):
        batch = to_list(batch)
        if self._inputs is not None:
            # explicit input spec: everything after the declared inputs is
            # labels (mirrors the reference's inputs/labels adapters)
            n_in = len(to_list(self._inputs))
            return batch[:n_in], batch[n_in:]
        if (self._loss is None and not self._metrics) or len(batch) < 2:
            return batch, []
        # convention: last element(s) are labels; single label by default
        n_lab = len(to_list(self._labels)) if self._labels else 1
        return batch[:-n_lab], batch[-n_lab:]

    def _run_one_epoch(self, loader, cbks, mode, epoch=0, ckpt=None,
                       skip_steps=0, resume_rng=None):
        logs = {}
        for m in self._metrics:
            m.reset()
        if mode == "train":
            # replaying a resumed epoch must draw the SAME shuffle
            # permutation the crashed run drew, so the sampler sees the
            # epoch-start numpy state; the save-time state is restored
            # once the skip completes (below).  Metric accumulations of
            # the already-consumed steps are NOT restored (documented
            # resume contract).
            if skip_steps and resume_rng is not None and \
                    resume_rng.get("numpy_epoch_start") is not None:
                np.random.set_state(resume_rng["numpy_epoch_start"])
            self._epoch_np_state = np.random.get_state()
        skipped = 0
        for step, batch in enumerate(loader):
            if step < skip_steps:
                skipped += 1
                continue
            if skipped and resume_rng is not None and \
                    resume_rng.get("numpy") is not None:
                np.random.set_state(resume_rng["numpy"])
                skipped = 0
            inputs, labels = self._split_batch(batch)
            getattr(cbks, f"on_{mode}_batch_begin")(step, logs)
            if mode == "train":
                loss, metrics = self.train_batch(inputs, labels)
                # an unsynced step returns the raw device array — leave it
                # on device (logs keep the last synced loss)
                if isinstance(loss, np.ndarray):
                    logs["loss"] = float(loss.reshape(-1)[0])
            else:
                loss, metrics = self.eval_batch(inputs, labels)
                if loss is not None:
                    logs["loss"] = float(np.asarray(loss).reshape(-1)[0])
            for m, res in zip(self._metrics, metrics):
                for name, val in zip(to_list(m.name()), to_list(res)):
                    logs[name] = val
            bs = inputs[0].shape[0] if inputs and inputs[0].shape else 1
            logs["batch_size"] = bs
            getattr(cbks, f"on_{mode}_batch_end")(step, logs)
            if mode == "train" and ckpt is not None:
                state_fn = (lambda e=epoch, s=step:
                            self._train_state(e, s))
                saved = ckpt.maybe_save(self._train_steps, state_fn)
                if ckpt.preempted:
                    # emergency checkpoint: the in-flight step finished
                    # above; persist, then exit the loop cleanly
                    if saved:
                        ckpt.wait()
                    else:
                        ckpt.save(self._train_steps, state_fn(), wait=True)
                    self.stop_training = True
                    break
        # end-of-epoch accumulated metric values
        for m in self._metrics:
            for name, val in zip(to_list(m.name()), to_list(m.accumulate())):
                logs[name] = val
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        if _callbacks is not None:
            cbks = _callbacks
        else:
            cbks = config_callbacks(callbacks, model=self, epochs=1,
                                    steps=steps, log_freq=log_freq,
                                    verbose=verbose,
                                    metrics=self._metrics_name())
        cbks.on_eval_begin({"steps": steps})
        logs = self._run_one_epoch(loader, cbks, "eval")
        if _callbacks is None:
            cbks.on_eval_end(logs)
        return {k: v for k, v in logs.items() if k != "batch_size"}

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, False,
                                   num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                metrics=[])
        cbks.on_predict_begin({})
        outputs = None
        for step, batch in enumerate(loader):
            batch = to_list(batch)
            batch, _ = self._split_batch(batch)  # drop trailing labels
            cbks.on_predict_batch_begin(step, {})
            outs = self.predict_batch(batch)
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
            cbks.on_predict_batch_end(step, {})
        cbks.on_predict_end({})
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(slot, axis=0) for slot in outputs]
        return outputs

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network)
