"""Continuous-batching serving engine (`paddle_tpu/inference/serving.py`).

Mirrors the capability of the reference's paged decode service
(`fused_multi_transformer_op.cu.h` cache-KV branch behind
`analysis_predictor.h:100` + a request scheduler): staggered requests
stream through ONE compiled decode program, joining free slots/blocks
mid-flight and releasing them on finish, at exact token parity with the
whole-batch compiled `generate`.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def prompts():
    rng = np.random.RandomState(0)
    return (rng.randint(1, 1000, (12,)), rng.randint(1, 1000, (30,)),
            rng.randint(1, 1000, (7,)))


def test_three_staggered_requests_one_program(model):
    """Requests arrive mid-flight; every one decodes through the SAME
    compiled step (program cache size 1) and matches generate()."""
    eng = ServingEngine(model, max_batch=3, max_context=128, block_size=16)
    p1, p2, p3 = prompts()
    r1 = eng.add_request(Request(p1, max_new_tokens=10))
    eng.step()
    eng.step()                                   # r1 alone for 2 steps
    r2 = eng.add_request(Request(p2, max_new_tokens=8))
    eng.step()                                   # r1 + r2
    r3 = eng.add_request(Request(p3, max_new_tokens=12))
    done = eng.run()                             # all three to completion

    assert {r.rid for r in done} == {r1.rid, r2.rid, r3.rid}
    # exactly ONE decode program compiled for the whole run (the k=1
    # device-sampling tick; `_decode_fn` is the host-sampling fallback's
    # cache and must stay empty so the two variants never cross-talk)
    progs = ([eng._decode_fn] if eng._decode_fn is not None else []) \
        + list(eng._tick_fns.values())
    assert len(progs) == 1
    for req, prompt in ((r1, p1), (r2, p2), (r3, p3)):
        assert len(req.output_ids) == req.max_new_tokens
        ref = model.generate(
            paddle.to_tensor(np.asarray(prompt, np.int32)[None]),
            max_new_tokens=req.max_new_tokens, cache_impl="paged")
        ref_new = np.asarray(ref._value)[0, len(prompt):]
        np.testing.assert_array_equal(req.output_ids, ref_new)


def test_blocks_and_slots_recycle(model):
    """Finished sequences return their blocks and slots; a queue deeper
    than max_batch drains through recycled capacity."""
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    total = eng.num_blocks
    rng = np.random.RandomState(1)
    reqs = [eng.add_request(Request(rng.randint(1, 1000, (5 + 3 * i,)),
                                    max_new_tokens=4 + i))
            for i in range(5)]                   # 5 requests, 2 slots
    done = eng.run()
    assert len(done) == 5
    st = eng.stats()
    assert st["free_blocks"] == total and st["reserved"] == 0
    assert st["active"] == 0 and st["waiting"] == 0
    for r in reqs:
        assert r.done and len(r.output_ids) == r.max_new_tokens


def test_eos_early_stop_frees_reservation(model):
    """eos mid-decode finishes the request and returns unused growth
    blocks to the pool."""
    # discover greedy streams for a handful of prompts, then declare a
    # LATER token of a non-degenerate stream eos.  The chosen eos must
    # differ from the ADMISSION token (output_ids[0]): an untrained
    # model's greedy decode often collapses to one repeated token, and
    # `eos == token0` used to finish the request at admission instead of
    # mid-decode (the tier-1 seed flake this fixture pin removes)
    probe_eng = ServingEngine(model, max_batch=4, max_context=64,
                              block_size=16)
    rng = np.random.RandomState(11)
    prompts_ = [rng.randint(1, 1000, (n,)).astype(np.int32)
                for n in (3, 5, 6, 7)]
    probes = [probe_eng.add_request(Request(q, max_new_tokens=8))
              for q in prompts_]
    probe_eng.run()

    def usable(req):
        first = req.output_ids[0]
        return next((t for t in req.output_ids[1:] if t != first), None)

    pick = next(((q, r, usable(r)) for q, r in zip(prompts_, probes)
                 if usable(r) is not None), None)
    assert pick is not None, \
        "every probe stream collapsed to its admission token: " \
        f"{[r.output_ids for r in probes]}"
    p, probe, eos = pick
    stop_at = probe.output_ids.index(eos)        # first occurrence
    eng2 = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    r = eng2.add_request(Request(p, max_new_tokens=30, eos_token_id=eos))
    eng2.run()
    assert r.done
    # same prompt -> same greedy stream: stopped exactly at the eos
    assert r.output_ids == probe.output_ids[:stop_at + 1]
    assert len(r.output_ids) >= 2                # genuinely mid-decode
    st = eng2.stats()
    assert st["free_blocks"] == eng2.num_blocks and st["reserved"] == 0


def test_admission_respects_capacity(model):
    """A request that cannot fit its worst case is queued, not admitted;
    oversized requests are rejected outright."""
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                        num_blocks=4)            # 64 tokens of pool
    with pytest.raises(ValueError, match="max_context"):
        eng.add_request(Request(np.arange(1, 60), max_new_tokens=30))
    big = eng.add_request(Request(np.arange(1, 33), max_new_tokens=31))
    small = eng.add_request(Request(np.arange(1, 5), max_new_tokens=4))
    eng.step()
    # big reserves ceil(63/16)=4 blocks less pad rounding — the second
    # request must wait until big's blocks free up
    assert eng.stats()["waiting"] >= 1 or small.done is False
    eng.run()
    assert big.done and small.done


@pytest.mark.slow  # 12s measured: compiles the sampling tick variant; test_three_staggered_requests_one_program keeps the fast multi-request pin
def test_sampling_requests_mix_with_greedy(model):
    """Per-slot sampling params are device inputs: a sampling request and
    a greedy request share the same compiled step."""
    eng = ServingEngine(model, max_batch=2, max_context=64, block_size=16)
    p1, p2, _ = prompts()
    g = eng.add_request(Request(p1[:8], max_new_tokens=6))
    s = eng.add_request(Request(p2[:8], max_new_tokens=6, do_sample=True,
                                temperature=0.8, top_k=50, seed=7))
    eng.run()
    ref = model.generate(
        paddle.to_tensor(np.asarray(p1[:8], np.int32)[None]),
        max_new_tokens=6, cache_impl="paged")
    np.testing.assert_array_equal(
        g.output_ids, np.asarray(ref._value)[0, 8:])
    assert len(s.output_ids) == 6


@pytest.mark.slow  # 12s measured: mixed prefill/decode tick compile; the staggered-requests fast pin covers one-program batching
def test_mixed_ticks_no_demotion_and_reproducible(model):
    """On-device sampling keeps a mixed greedy+sampled batch on the FULL
    k-step tick (no k=1 demotion), the sampled stream is reproducible
    from the request seed, and — because each token is drawn from
    fold_in(key(seed), position) — the stream is INDEPENDENT of the tick
    size."""
    p1, p2, _ = prompts()

    def serve(steps_per_tick):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=steps_per_tick)
        g = eng.add_request(Request(p1[:8], max_new_tokens=9))
        s = eng.add_request(Request(p2[:8], max_new_tokens=9,
                                    do_sample=True, temperature=0.9,
                                    top_k=40, seed=1234))
        eng.run()
        return eng, g, s

    eng4, g4, s4 = serve(4)
    # budget 9 = 1 prefill token + 8 decode steps = two FULL k=4 ticks;
    # the old host-side sampler demoted this to eight k=1 ticks
    assert eng4.steps == 8 and eng4.stats()["ticks"] == 2
    assert len(s4.output_ids) == 9
    # greedy row unaffected by its sampling neighbour
    ref = model.generate(
        paddle.to_tensor(np.asarray(p1[:8], np.int32)[None]),
        max_new_tokens=9, cache_impl="paged")
    np.testing.assert_array_equal(g4.output_ids,
                                  np.asarray(ref._value)[0, 8:])
    # same seeds -> same stream; k=1 ticks -> same stream too
    _, _, s4b = serve(4)
    assert s4b.output_ids == s4.output_ids
    _, _, s1 = serve(1)
    assert s1.output_ids == s4.output_ids


def test_device_filter_matches_host_filter():
    """`_process_logits_rows` (per-row params, the decode tick's filter)
    equals the scalar host `_process_logits` row by row on a fixed-logits
    case, across greedy-ish/temperature/top-k/top-p mixes."""
    import jax.numpy as jnp
    from paddle_tpu.models.generation import (_process_logits,
                                              _process_logits_rows)
    rng = np.random.RandomState(3)
    V = 50
    params = [(1.0, 0, 1.0), (0.7, 0, 1.0), (1.0, 10, 1.0),
              (1.0, 0, 0.9), (0.8, 12, 0.85), (1.3, 3, 0.5)]
    logits = rng.randn(len(params), V).astype(np.float32) * 3
    rows = _process_logits_rows(
        jnp.asarray(logits),
        jnp.asarray([t for t, _, _ in params], jnp.float32),
        jnp.asarray([k for _, k, _ in params], jnp.int32),
        jnp.asarray([p for _, _, p in params], jnp.float32))
    for i, (t, k, p) in enumerate(params):
        want = _process_logits(jnp.asarray(logits[i:i + 1]), t, k, p)
        np.testing.assert_allclose(np.asarray(rows)[i], np.asarray(want)[0],
                                   rtol=1e-6, atol=1e-6)


def test_device_sampler_matches_host_distribution():
    """Tokens drawn the way the decode tick draws them (per-slot
    fold_in(key(seed), position) + categorical over the filtered logits)
    follow the host sampler's distribution on a fixed-logits case:
    same support (filtered-out tokens never drawn) and matching
    frequencies."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.generation import (_process_logits,
                                              _process_logits_rows)
    rng = np.random.RandomState(5)
    V = 24
    logits = (rng.randn(V) * 2).astype(np.float32)
    t, k, p = 0.8, 12, 0.9
    # host distribution (the Request._sample construction)
    filtered = np.asarray(_process_logits(
        jnp.asarray(logits)[None], t, k, p))[0]
    probs = np.exp(filtered - filtered.max())
    probs = probs / probs.sum()
    # device draws: one per position, as the tick program folds the key
    N = 4000
    frows = _process_logits_rows(
        jnp.asarray(np.tile(logits, (N, 1))),
        jnp.full((N,), t, jnp.float32), jnp.full((N,), k, jnp.int32),
        jnp.full((N,), p, jnp.float32))
    keys = jax.vmap(lambda pos: jax.random.fold_in(
        jax.random.key(jnp.uint32(77)), pos))(jnp.arange(N))
    draws = np.asarray(jax.vmap(jax.random.categorical)(keys, frows))
    counts = np.bincount(draws, minlength=V) / N
    assert counts[probs == 0].sum() == 0          # support respected
    np.testing.assert_allclose(counts, probs, atol=0.05)


@pytest.mark.slow  # 10s measured: runs the engine twice (overlap on/off); xray's forced-boundary sampling parity stays fast
def test_overlap_matches_synchronous(model):
    """The double-buffered tick loop (FLAGS_serving_overlap) produces
    token-for-token the same streams as the synchronous loop, greedy and
    sampled alike, and releases every block/reservation."""
    p1, p2, p3 = prompts()

    def serve():
        eng = ServingEngine(model, max_batch=3, max_context=128,
                            block_size=16, steps_per_tick=2)
        reqs = [eng.add_request(Request(p1, max_new_tokens=10)),
                eng.add_request(Request(p2, max_new_tokens=7,
                                        do_sample=True, top_k=25,
                                        seed=42)),
                eng.add_request(Request(p3, max_new_tokens=12))]
        eng.run()
        return eng, [list(r.output_ids) for r in reqs]

    with flag_guard(serving_overlap=False):
        _, sync = serve()
    from paddle_tpu.observability import metrics as _metrics
    _metrics.reset()
    with flag_guard(serving_overlap=True):
        eng, ov = serve()
    assert ov == sync
    assert eng.stats()["free_blocks"] == eng.num_blocks
    assert eng.stats()["reserved"] == 0
    snap = _metrics.snapshot()
    assert snap["serving.overlap_dispatches"]["series"][0]["value"] > 0
    assert snap["serving.sampled_tokens"]["series"][0]["value"] >= 6


def test_overlap_eos_overrun_reclaims_everything(model):
    """A request that hits EOS while the NEXT tick is already in flight
    (overlap's EOS overrun) discards the overrun tokens, truncates at
    the first EOS, and still returns every block and reservation."""
    p = np.asarray([5, 6, 7], np.int32)
    probe_eng = ServingEngine(model, max_batch=2, max_context=64,
                              block_size=16)
    probe = probe_eng.add_request(Request(p, max_new_tokens=8))
    probe_eng.run()
    eos = probe.output_ids[-1]
    stop_at = probe.output_ids.index(eos)         # first occurrence
    with flag_guard(serving_overlap=True):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=4)
        r = eng.add_request(Request(p, max_new_tokens=30,
                                    eos_token_id=eos))
        eng.run()
    assert r.done
    assert r.output_ids == probe.output_ids[:stop_at + 1]
    assert eng.stats()["free_blocks"] == eng.num_blocks
    assert eng.stats()["reserved"] == 0


def test_llama_family_serves_at_parity():
    """The engine is model-agnostic over forward_with_cache: the Llama
    family (RoPE + GQA + RMSNorm) streams staggered requests at exact
    parity with its compiled generate."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    eng = ServingEngine(m, max_batch=2, max_context=64, block_size=16)
    rng = np.random.RandomState(0)
    p1 = rng.randint(1, 500, (9,))
    r1 = eng.add_request(Request(p1, max_new_tokens=6))
    eng.step()
    r2 = eng.add_request(Request(rng.randint(1, 500, (14,)),
                                 max_new_tokens=5))
    eng.run()
    assert len(r1.output_ids) == 6 and len(r2.output_ids) == 5
    ref = m.generate(paddle.to_tensor(np.asarray(p1, np.int32)[None]),
                     max_new_tokens=6, cache_impl="paged")
    np.testing.assert_array_equal(r1.output_ids,
                                  np.asarray(ref._value)[0, 9:])
