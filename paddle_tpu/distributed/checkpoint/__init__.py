"""Distributed (sharded) checkpoint: save/load with reshard-on-load, plus
the fault-tolerance layer (atomic versioned commits + auto-resume policy).

Parity: `python/paddle/distributed/checkpoint/` — save_state_dict
(`save_state_dict.py:104`), load_state_dict (`load_state_dict.py:377`),
Metadata (`metadata.py:20`).  `CheckpointManager` (manager.py) is the
TPU-native analogue of orbax's atomic-commit CheckpointManager.
"""

from .load_state_dict import load_metadata, load_state_dict, read_state_dict
from .manager import (CheckpointManager, all_steps, clear_preemption,
                      latest_complete, preemption_requested,
                      request_preemption, verify_version)
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .save_state_dict import (plan_save, save_state_dict, wait_async_save,
                              write_planned)
from .utils import flatten_state_dict, unflatten_state_dict

__all__ = [
    "save_state_dict", "load_state_dict", "load_metadata", "wait_async_save",
    "read_state_dict", "plan_save", "write_planned",
    "CheckpointManager", "latest_complete", "all_steps", "verify_version",
    "preemption_requested", "request_preemption", "clear_preemption",
    "Metadata", "LocalTensorMetadata", "LocalTensorIndex",
    "flatten_state_dict", "unflatten_state_dict",
]
