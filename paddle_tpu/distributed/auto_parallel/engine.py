"""Auto-parallel static Engine: capture + compile the whole distributed step.

Parity: `python/paddle/distributed/auto_parallel/static/engine.py`
(`Engine.fit` `:1146`, `prepare` `:1710`, `_build` `:752`) and the
`Parallelizer` pipeline (`parallelizer_v2.py`: Completer -> Partitioner ->
Resharder -> passes).

TPU-native redesign: the reference traces the model into a serial Program,
propagates dist attrs op-by-op (Completer), splits it per rank (Partitioner)
and inserts communication (Resharder).  On TPU that whole pipeline IS
jit + GSPMD: the user marks parameter/input placements (``shard_tensor``),
`jit.to_static` captures the full train step (forward + loss + backward +
optimizer) as one program, and XLA's sharding propagation + SPMD partitioner
emit the per-device program with collectives over ICI.  The Engine therefore
reduces to: build the step function from (model, loss, optimizer, strategy),
apply the strategy's capture-time decisions (AMP context, recompute,
in-step gradient merge, ZeRO state sharding), shard incoming host batches
over the mesh's data axis, and drive the epoch loop.
"""

from __future__ import annotations

import numbers
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from .process_mesh import ProcessMesh
from .strategy import Strategy

__all__ = ["Engine", "DistModel"]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class Engine:
    """`auto.Engine(model, loss, optimizer, metrics, strategy)`.

    The data-parallel mesh axis is taken to be the FIRST axis of the
    parameter mesh (reference topology order puts dp outermost,
    `fleet/base/topology.py:290`) unless an axis is literally named "dp"
    or "data".
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy: Optional[Strategy] = None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = _to_list(metrics)
        self._strategy = strategy or Strategy()
        self._compiled: Dict[Any, Any] = {}
        self._mesh: Optional[ProcessMesh] = None
        self._data_axis: Optional[str] = None
        self._scaler = None
        self._prepared = False
        self.history: Dict[str, List[float]] = {}

    # ------------------------------------------------------------- topology
    def _parameters(self):
        if self._model is None or not hasattr(self._model, "parameters"):
            return []
        return self._model.parameters()

    def _set_mode(self, train: bool):
        if self._model is None:
            return
        if train and hasattr(self._model, "train"):
            self._model.train()
        elif not train and hasattr(self._model, "eval"):
            self._model.eval()

    def _discover_mesh(self):
        if self._mesh is not None or self._model is None:
            return
        for p in self._parameters():
            attr = getattr(p, "_dist_attr", None)
            if attr and isinstance(attr, dict) and attr.get("mesh") is not None:
                self._mesh = attr["mesh"]
                break
        if self._mesh is not None:
            names = self._mesh.dim_names
            for cand in ("dp", "data", "batch"):
                if cand in names:
                    self._data_axis = cand
                    return
            self._data_axis = names[0]

    def _shard_batch(self, x):
        """Lay a host batch out over the mesh's data axis (the reference's
        dist dataloader splits the batch per dp rank; here the global batch
        is placed sharded so GSPMD sees the dp dimension)."""
        if isinstance(x, Tensor):
            t = x
        else:
            t = Tensor(np.asarray(x))
        if self._mesh is None or self._data_axis is None or t.ndim == 0:
            return t
        degree = dict(zip(self._mesh.dim_names, self._mesh.shape)
                      )[self._data_axis]
        if degree <= 1 or t.shape[0] % degree != 0:
            return t
        sh = NamedSharding(self._mesh.jax_mesh(),
                           P(self._data_axis, *([None] * (t.ndim - 1))))
        out = Tensor._wrap(jax.device_put(t._value, sh),
                           stop_gradient=t.stop_gradient)
        return out

    # ----------------------------------------------------------------- step
    def _amp_ctx(self):
        import contextlib
        amp_cfg = self._strategy.amp
        if not amp_cfg.enable:
            return contextlib.nullcontext()
        from ... import amp as _amp
        return _amp.auto_cast(
            True, level=amp_cfg.level.upper(), dtype=amp_cfg.dtype,
            custom_white_list=list(amp_cfg.custom_white_list) or None,
            custom_black_list=list(amp_cfg.custom_black_list) or None)

    def _forward(self, *inputs):
        if self._strategy.recompute.enable:
            from ..fleet.recompute import recompute
            return recompute(self._model, *inputs)
        return self._model(*inputs)

    def _build_step(self, mode: str, n_inputs: int):
        merge = self._strategy.gradient_merge
        k = max(int(merge.k_steps), 1) if merge.enable else 1

        if mode == "train":
            def step(*args):
                ins, labs = args[:n_inputs], args[n_inputs:]
                total = None
                for i in range(k):
                    mi = [x[i::k] if k > 1 else x for x in ins]
                    ml = [y[i::k] if k > 1 else y for y in labs]
                    with self._amp_ctx():
                        out = _to_list(self._forward(*mi))
                        loss = self._loss(*(out + ml))
                    contrib = loss / k if (k > 1 and merge.avg) else loss
                    if self._scaler is not None:
                        self._scaler.scale(contrib).backward()
                    else:
                        contrib.backward()
                    total = loss if total is None else total + loss
                if self._scaler is not None:
                    self._scaler.step(self._optimizer)
                else:
                    self._optimizer.step()
                self._optimizer.clear_grad()
                return total / k
        elif mode == "eval":
            def step(*args):
                ins, labs = args[:n_inputs], args[n_inputs:]
                out = _to_list(self._model(*ins))
                res = out
                if self._loss is not None:
                    res = [self._loss(*(out + list(labs)))] + out
                return res
        else:  # predict
            def step(*args):
                return _to_list(self._model(*args))
        return step

    def _get_step(self, mode: str, n_inputs: int):
        key = (mode, n_inputs)
        # fp16 dynamic loss scaling branches on found_inf host-side: eager
        if self._scaler is not None and mode == "train":
            return self._build_step(mode, n_inputs)
        if key not in self._compiled:
            from ...jit import to_static
            self._compiled[key] = to_static(
                self._build_step(mode, n_inputs), full_graph=True)
        return self._compiled[key]

    # ------------------------------------------------------------ user API
    def prepare(self, inputs_spec=None, labels_spec=None, main_program=None,
                startup_program=None, mode: str = "train"):
        """Finalize topology + AMP machinery (reference `engine.py:1710`)."""
        self._discover_mesh()
        amp_cfg = self._strategy.amp
        if amp_cfg.enable and amp_cfg.dtype == "float16" \
                and self._scaler is None:
            from ... import amp as _amp
            self._scaler = _amp.GradScaler(
                init_loss_scaling=amp_cfg.init_loss_scaling)
        if self._strategy.sharding.enable and self._optimizer is not None \
                and self._mesh is not None:
            # ZeRO: optimizer accumulators inherit each parameter's sharding
            # plus a shard over the data axis when the param is replicated
            from .api import shard_optimizer
            axis = self._data_axis
            jmesh = self._mesh.jax_mesh()

            def _shard_state(name, p, arr):
                try:
                    spec = p._value.sharding.spec
                except Exception:
                    return arr
                entries = list(spec) + [None] * (arr.ndim - len(list(spec)))
                if axis is not None and arr.ndim:
                    used = set()
                    for e in entries:
                        used.update(e if isinstance(e, tuple) else (e,))
                    dims = dict(zip(self._mesh.dim_names, self._mesh.shape))
                    if axis not in used:
                        for d in range(arr.ndim):
                            if entries[d] is None and \
                                    arr.shape[d] % dims[axis] == 0:
                                entries[d] = axis
                                break
                return jax.device_put(
                    arr, NamedSharding(jmesh, P(*entries)))

            self._optimizer = shard_optimizer(self._optimizer, _shard_state)
        self._prepared = True
        return self

    def _ensure_prepared(self):
        if not self._prepared:
            self.prepare()

    def _make_loader(self, data, batch_size, shuffle=False, num_workers=0,
                     drop_last=False):
        """drop_last=True only for training (keeps the compiled step's
        batch shape fixed); evaluate/predict must see every sample, at the
        cost of one extra compile for a ragged final batch."""
        from ... import io
        if isinstance(data, io.DataLoader):
            sampler = getattr(data, "batch_sampler", None)
            already_drops = getattr(sampler, "drop_last",
                                    getattr(data, "drop_last", False))
            if drop_last and not already_drops and \
                    getattr(data, "dataset", None) is not None and \
                    not getattr(data, "_iterable_mode", False):
                # a ragged final batch would violate the compiled step's
                # fixed shape; rebuild the loader over the same dataset
                bs = getattr(sampler, "batch_size", None) or batch_size
                return io.DataLoader(
                    data.dataset, batch_size=bs, shuffle=shuffle,
                    collate_fn=data._custom_collate,
                    num_workers=data.num_workers, drop_last=True)
            return data
        if isinstance(data, (list, tuple)) and data and \
                isinstance(data[0], (np.ndarray, Tensor)):
            data = io.TensorDataset([t if isinstance(t, Tensor)
                                     else Tensor(np.asarray(t))
                                     for t in data])
        return io.DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                             num_workers=num_workers, drop_last=drop_last)

    def dataloader(self, dataset, batch_size=1, shuffle=False, num_workers=0,
                   mode: str = "train"):
        """Reference `engine.dataloader`: a loader whose batches come out
        already sharded over the data axis."""
        self._ensure_prepared()
        loader = self._make_loader(dataset, batch_size, shuffle, num_workers,
                                   drop_last=(mode == "train"))
        engine = self

        def it():
            for batch in loader:
                yield [engine._shard_batch(b) for b in _to_list(batch)]
        return it()

    def _run_batch(self, mode: str, inputs, labels):
        inputs = [self._shard_batch(x) for x in _to_list(inputs)]
        labels = [self._shard_batch(y) for y in _to_list(labels)]
        self._set_mode(mode == "train")
        step = self._get_step(mode, len(inputs))
        return step(*(inputs + labels))

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, valid_data=None,
            num_workers=0, verbose=1, shuffle=True):
        self._ensure_prepared()
        if self._optimizer is None or self._loss is None:
            raise RuntimeError(
                "Engine.fit needs both a loss and an optimizer")
        split = train_sample_split
        logs: Dict[str, List[float]] = {"loss": []}
        for epoch in range(epochs):
            loader = self._make_loader(train_data, batch_size,
                                       shuffle=shuffle,
                                       num_workers=num_workers,
                                       drop_last=True)
            for step_i, batch in enumerate(loader):
                if steps_per_epoch is not None and step_i >= steps_per_epoch:
                    break
                batch = _to_list(batch)
                ns = split if split is not None else max(len(batch) - 1, 1)
                loss = self._run_batch("train", batch[:ns], batch[ns:])
                lv = float(np.asarray(jax.device_get(loss._value)))
                logs["loss"].append(lv)
                if verbose and step_i % log_freq == 0:
                    print(f"epoch {epoch} step {step_i}: loss {lv:.6f}")
            if valid_data is not None:
                self.evaluate(valid_data, batch_size=batch_size,
                              verbose=verbose)
        self.history = logs
        return logs

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, verbose=1, num_workers=0):
        self._ensure_prepared()
        losses = []
        loader = self._make_loader(valid_data, batch_size)
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            batch = _to_list(batch)
            ns = valid_sample_split if valid_sample_split is not None \
                else max(len(batch) - 1, 1)
            res = self._run_batch("eval", batch[:ns], batch[ns:])
            if self._loss is not None:
                losses.append(float(np.asarray(
                    jax.device_get(res[0]._value))))
        out = {"loss": float(np.mean(losses))} if losses else {}
        if verbose and losses:
            print(f"eval: loss {out['loss']:.6f}")
        return out

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, num_workers=0, verbose=0):
        self._ensure_prepared()
        outs = []
        loader = self._make_loader(test_data, batch_size)
        for step_i, batch in enumerate(loader):
            if steps is not None and step_i >= steps:
                break
            batch = _to_list(batch)
            ns = test_sample_split if test_sample_split is not None \
                else len(batch)
            res = self._run_batch("predict", batch[:ns], [])
            outs.append([np.asarray(jax.device_get(r._value))
                         for r in _to_list(res)])
        return outs

    # --------------------------------------------------------- save / load
    def _inner_opt(self):
        if self._optimizer is None:
            return None
        return getattr(self._optimizer, "_inner", self._optimizer)

    def save(self, path: str, training: bool = True):
        from ...framework import io as fio
        fio.save(self._model.state_dict(), path + ".pdparams")
        opt = self._inner_opt()
        if training and opt is not None:
            # accumulator keys go out in structured form so another process
            # (different global param-name counter) can restore them
            fio.save(opt.remap_state_keys(self._model, opt.state_dict(),
                                          to_structured=True),
                     path + ".pdopt")

    def load(self, path: str, strict: bool = True, load_optimizer: bool = True):
        import os
        from ...framework import io as fio
        self._model.set_state_dict(fio.load(path + ".pdparams"))
        opt = self._inner_opt()
        if load_optimizer and opt is not None \
                and os.path.exists(path + ".pdopt"):
            opt.set_state_dict(opt.remap_state_keys(
                self._model, fio.load(path + ".pdopt"), to_structured=False))
        self._compiled = {}  # new weights invalidate donated buffers

    # parity accessors
    @property
    def main_program(self):  # the compiled step IS the program
        return next(iter(self._compiled.values()), None)

    def cost(self, mode="train"):
        """Rough cost model hook (reference has static/cost/): returns the
        captured program's FLOPs estimate via XLA cost analysis."""
        fn = self.main_program
        if fn is None:
            return None
        return getattr(fn, "cost_analysis", lambda: None)()


class DistModel:
    """Callable returned by `dist.to_static(layer, loader, loss, opt)`:
    runs the compiled distributed step (reference
    `auto_parallel/api.py:2097` returns the same shape of object)."""

    def __init__(self, engine: Engine, n_inputs: int = 1):
        self._engine = engine
        # train mode needs BOTH pieces; an optimizer without a loss cannot
        # form a train step
        self._mode = "train" if (engine._optimizer is not None
                                 and engine._loss is not None) else "predict"
        self._n_inputs = n_inputs

    def train(self):
        self._mode = "train"
        self._engine._set_mode(True)
        return self

    def eval(self):
        self._mode = "eval"
        self._engine._set_mode(False)
        return self

    def predict(self):
        self._mode = "predict"
        return self

    def __call__(self, *args):
        eng = self._engine
        eng._ensure_prepared()
        if self._mode == "train" and eng._loss is None:
            raise RuntimeError("DistModel in train mode needs a loss; "
                               "pass loss= to dist.to_static or call "
                               ".predict()/.eval()")
        if self._mode == "predict":
            res = eng._run_batch("predict", list(args), [])
            # mirror the model's own forward: single output unwrapped
            return res[0] if isinstance(res, list) and len(res) == 1 else res
        n = self._n_inputs
        res = eng._run_batch(self._mode, list(args[:n]), list(args[n:]))
        return res if not isinstance(res, list) else res[0]

    def state_dict(self, *a, **k):
        return self._engine._model.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._engine._model.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._engine._model.parameters(*a, **k)
