"""Elastic ZeRO-3 (ISSUE 19): the fused one-dispatch stage-3 train step
and reshard-on-resume across world sizes.

The headline contracts pinned here:

* the fused step traces parameter gathering INSIDE the program —
  per-bucket `all_gather` ops in the lowered HLO (two text occurrences
  per bucket: the op and its sharding annotation), gradients
  reduce-scatter back via the AD transpose, and the whole step is ONE
  compiled program (the compile-tracker entry never recompiles after
  warmup — the eager-collective regression R014 also lints for);
* grain=0 numerics match the serial reference step (loss near-exact,
  params within a norm tolerance after Adam steps — first-step Adam is
  sign descent, infinitely sensitive where g ~ 0);
* with a reduction grain the step is BIT-exact across world sizes:
  save at dp=4, resume at dp=2, resume again at dp=4 — params AND both
  Adam moments bit-match a never-interrupted run (the flat layout's
  pad region is an invariant 0, so the trailing-dim resize on restore
  is lossless);
* `restore_into` refuses a shape mismatch unless the caller opts into
  `resize_trailing` — elastic resume is explicit, not a silent cast.
"""

import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from paddle_tpu import flags as fl
from paddle_tpu.distributed.checkpoint.manager import CheckpointManager
from paddle_tpu.distributed.fleet import hybrid_step as hs
from paddle_tpu.distributed.fleet.sharding import (flat_shard_layout,
                                                   plan_zero3_buckets)
from paddle_tpu.observability import compile_tracker as obs_compile


def _cfg(dp, **kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                seq_len=16, pp=1, mp=1, dp=dp, n_microbatches=2,
                sequence_parallel=False, remat=False, zero_stage=3)
    base.update(kw)
    return hs.HybridConfig(**base)


def _mesh(dp):
    return Mesh(np.array(jax.devices()[:dp]), ("dp",))


@pytest.fixture(scope="module")
def params():
    return hs.init_gpt_params(jax.random.PRNGKey(0), _cfg(4))


@pytest.fixture(scope="module")
def ids():
    return jax.random.randint(jax.random.PRNGKey(1), (2, 8, 16), 0, 64)


# ------------------------------------------------------------ layout math

def test_flat_shard_layout():
    """Fp is the smallest degree-multiple >= F; scalars flatten to 1."""
    assert flat_shard_layout((3, 5), 4) == (15, 16)
    assert flat_shard_layout((8,), 4) == (8, 8)
    assert flat_shard_layout((), 4) == (1, 4)
    F, Fp = flat_shard_layout((7, 11), 3)
    assert F == 77 and Fp % 3 == 0 and Fp - F < 3


def test_bucket_plan():
    """Consecutive leaves group under the MiB limit; 0 = one per leaf;
    every index appears exactly once, in order."""
    mb = 1 << 20
    sizes = [mb, mb, 3 * mb, mb // 2, mb // 2]
    got = plan_zero3_buckets(sizes, 2)
    assert got == [[0, 1], [2], [3, 4]]
    assert plan_zero3_buckets(sizes, 0) == [[i] for i in range(len(sizes))]
    # an oversized leaf gets its own bucket rather than being dropped
    assert plan_zero3_buckets([5 * mb], 2) == [[0]]
    flat = [i for b in plan_zero3_buckets(sizes, 1) for i in b]
    assert flat == list(range(len(sizes)))


# ------------------------------------------------- fused step: numerics

def test_zero3_shard_update_adam_reference_and_pad_invariance():
    """Fast twin of the @slow serial-parity and resume drills, at the
    update-rule level: the fused shard update is textbook Adam against
    a float64 numpy reference (element-wise — no reduction order in
    play at this level), and a (0, 0, 0) pad triple under a zero
    gradient maps back to exactly (0, 0, 0) — the invariant that makes
    the trailing resize on elastic resume lossless."""
    from paddle_tpu.optimizer.fused import zero3_shard_update
    hp = dict(learning_rate=1e-3, beta1=0.9, beta2=0.999, eps=1e-8)
    rng = np.random.RandomState(0)
    p = rng.randn(33).astype(np.float32)
    g = rng.randn(33).astype(np.float32)
    m = rng.randn(33).astype(np.float32) * 0.1
    v = np.abs(rng.randn(33)).astype(np.float32) * 0.1
    for t in (1.0, 7.0):
        (p2,), (m2,), (v2,) = zero3_shard_update(
            [jnp.asarray(p)], [jnp.asarray(g)], [jnp.asarray(m)],
            [jnp.asarray(v)], jnp.float32(t), **hp)
        rm = 0.9 * m + 0.1 * g
        rv = 0.999 * v + 0.001 * np.square(g)
        ref = p - 1e-3 * (rm / (1 - 0.9 ** t)) / (
            np.sqrt(rv / (1 - 0.999 ** t)) + 1e-8)
        np.testing.assert_allclose(np.asarray(p2), ref, rtol=2e-5,
                                   atol=2e-6)
        np.testing.assert_allclose(np.asarray(m2), rm, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(v2), rv, rtol=1e-6)
    z = [jnp.zeros(5)]
    (pz,), (mz,), (vz,) = zero3_shard_update(
        z, z, z, z, jnp.float32(3.0), **hp)
    for arr in (pz, mz, vz):
        assert (np.asarray(arr) == 0).all()


@pytest.mark.slow  # ~13s measured: compiles the fused zero3 step AND
                   # the serial reference; the fast twins are the
                   # update-rule parity above + the HLO/program pin
                   # below (which compiles only the zero3 step)
def test_zero3_grain0_parity_vs_serial(params, ids):
    """The fused sharded-resident step trains like the serial reference:
    losses near-exact per step, params within a norm tolerance after 3
    Adam steps (reduction-order drift through psum is amplified by
    first-step Adam's sign-descent behavior, so element-wise compare
    is the wrong pin)."""
    cfg = _cfg(4)
    mesh = _mesh(4)
    fp, m, v = hs.init_zero3_state(params, mesh)
    step = hs.make_zero3_train_step(mesh, cfg)
    sp = params
    sm = jax.tree_util.tree_map(jnp.zeros_like, params)
    sv = jax.tree_util.tree_map(jnp.zeros_like, params)
    for t in range(3):
        sl, sp, sm, sv = hs.serial_train_step(
            sp, sm, sv, jnp.float32(t + 1), ids, cfg)
        loss, fp, m, v = step(fp, m, v, jnp.float32(t + 1), ids)
        assert abs(float(sl) - float(loss)) < 2e-4, (t, float(sl),
                                                     float(loss))
    for a, b in zip(jax.tree_util.tree_leaves(hs.zero3_unflatten(fp, cfg)),
                    jax.tree_util.tree_leaves(sp)):
        da = np.asarray(a).ravel().astype(np.float64)
        db = np.asarray(b).ravel().astype(np.float64)
        assert np.linalg.norm(da - db) <= 5e-3 * (np.linalg.norm(db)
                                                  + 1e-6)


def test_zero3_in_program_gathers_single_program(params, ids):
    """The perf contract: gathers live INSIDE the one program (HLO
    carries exactly two `all_gather` text occurrences per bucket;
    bucket_mb=0 degenerates to one bucket per leaf), and repeated
    steps never recompile — the compile-tracker entry stays at one
    compilation, which is what makes eager per-layer collectives
    (lint R014) structurally impossible here."""
    cfg = _cfg(4)
    mesh = _mesh(4)
    fp, m, v = hs.init_zero3_state(params, mesh)
    step = hs.make_zero3_train_step(mesh, cfg)
    txt = str(step.lower(fp, m, v, jnp.float32(1.0), ids).as_text())
    assert txt.count("all_gather") == 2 * len(step.buckets)
    with fl.flag_guard(zero3_bucket_mb=0.0):
        step0 = hs.make_zero3_train_step(mesh, cfg)
    n_leaves = len(jax.tree_util.tree_leaves(params))
    assert len(step0.buckets) == n_leaves
    # program-count pin: warmup compiles once, then the entry is frozen
    loss0, fp, m, v = step(fp, m, v, jnp.float32(1.0), ids)
    ent = obs_compile.get("hybrid.zero3_step")
    assert ent is not None and ent["compiles"] >= 1
    frozen = ent["compiles"]
    for t in range(2, 4):
        _, fp, m, v = step(fp, m, v, jnp.float32(t), ids)
    assert obs_compile.get("hybrid.zero3_step")["compiles"] == frozen


# ----------------------------------------------- elastic resume drills

def _run_steps(dp, grain, n, params, ids, state=None, t0=0):
    cfgd = _cfg(dp)
    meshd = _mesh(dp)
    if state is None:
        state = hs.init_zero3_state(params, meshd)
    st = hs.make_zero3_train_step(meshd, cfgd, grain=grain)
    fp, m, v = state
    for t in range(t0, t0 + n):
        _, fp, m, v = st(fp, m, v, jnp.float32(t + 1), ids)
    return fp, m, v


def _assert_bit_equal(a, b, what):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y)), what


@pytest.mark.slow  # ~19s measured: three grain-mode program builds
                   # (dp4/dp2/dp4) + the uninterrupted reference; fast
                   # resume coverage = the restore_into resize test
                   # below + the pad-invariance half of the update-rule
                   # twin above
def test_zero3_elastic_resume_bit_exact(params, ids):
    """The short form of the satellite drill: save at dp=4 after one
    step, resume at dp=2 for one step, resume back at dp=4 for one
    step — params and BOTH moments bit-match a never-interrupted
    3-step dp=4 run (the full-drill twin runs the longer schedule)."""
    cfg = _cfg(4)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        s4 = _run_steps(4, 4, 1, params, ids)
        hs.save_zero3_state(mgr, 1, *s4, 1.0, grain=4, wait=True)

        fp2, m2, v2, sn, gr = hs.load_zero3_state(mgr, _mesh(2), cfg)
        assert (sn, gr) == (1.0, 4)
        s2 = _run_steps(2, 4, 1, params, ids, (fp2, m2, v2), int(sn))
        hs.save_zero3_state(mgr, 2, *s2, 2.0, grain=4, wait=True)

        fp4, m4, v4, sn2, _ = hs.load_zero3_state(mgr, _mesh(4), cfg)
        sR = _run_steps(4, 4, 1, params, ids, (fp4, m4, v4), int(sn2))
        sU = _run_steps(4, 4, 3, params, ids)
        for name, a, b in zip("pmv", sR, sU):
            _assert_bit_equal(a, b, name)


@pytest.mark.slow  # ~35s measured: six program builds (dp4/dp2 at two
                   # grains) + two checkpoint round-trips
def test_zero3_elastic_resume_full_drill(params, ids):
    """The full satellite drill: multi-step segments across 4 -> 2 -> 4
    with a mid-segment grain the short drill doesn't cover, against the
    uninterrupted run — and the restored flat shards land back on the
    WIDER pad layout without disturbing live elements."""
    cfg = _cfg(4)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        s4 = _run_steps(4, 2, 2, params, ids)
        hs.save_zero3_state(mgr, 2, *s4, 2.0, grain=2, wait=True)

        fp2, m2, v2, sn, gr = hs.load_zero3_state(mgr, _mesh(2), cfg)
        assert gr == 2
        s2 = _run_steps(2, 2, 2, params, ids, (fp2, m2, v2), int(sn))
        hs.save_zero3_state(mgr, 4, *s2, 4.0, grain=2, wait=True)

        fp4, m4, v4, sn2, _ = hs.load_zero3_state(mgr, _mesh(4), cfg)
        sR = _run_steps(4, 2, 2, params, ids, (fp4, m4, v4), int(sn2))
        sU = _run_steps(4, 2, 6, params, ids)
        for name, a, b in zip("pmv", sR, sU):
            _assert_bit_equal(a, b, name)


def test_restore_into_requires_explicit_resize():
    """A world-size change shows up as a trailing-dim shape mismatch;
    the load path must REFUSE it unless the caller passes
    `resize_trailing=True` — and even then only a trailing-dim-only
    mismatch qualifies.  With the flag, growth zero-fills the overhang
    and shrink truncates (the pad region is an invariant 0 of the
    fused step, which is what makes this bit-exact)."""
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        mgr.save(1, {"s": {"a": jnp.arange(12, dtype=jnp.float32)}},
                 wait=True)

        def tgt(shape):
            return {"s": {"a": jnp.zeros(shape, jnp.float32)}}

        with pytest.raises(ValueError, match="shape mismatch"):
            mgr.restore_into(tgt((16,)), step=1)
        grown, _ = mgr.restore_into(tgt((16,)), step=1,
                                    resize_trailing=True)
        got = np.asarray(grown["s"]["a"])
        assert np.array_equal(got[:12], np.arange(12, dtype=np.float32))
        assert (got[12:] == 0).all()
        shrunk, _ = mgr.restore_into(tgt((8,)), step=1,
                                     resize_trailing=True)
        assert np.array_equal(np.asarray(shrunk["s"]["a"]),
                              np.arange(8, dtype=np.float32))
        # a rank/non-trailing mismatch never qualifies
        with pytest.raises(ValueError, match="resize_trailing"):
            mgr.restore_into(tgt((2, 12)), step=1, resize_trailing=True)


# ------------------------------------- offload staging contract (pin)

@pytest.mark.xfail(jax.default_backend() == "cpu", strict=False,
                   reason="XLA:CPU ignores host placement annotations "
                          "on compiled-program outputs; the pinned_host "
                          "round-trip is a TPU contract")
def test_offload_state_roundtrips_to_pinned_host(hybrid_mesh):
    """ZeRO-Offload staging contract (`_migrate_state`): between
    compiled steps EVERY optimizer accumulator must sit in
    `pinned_host` memory — the step stages host -> device -> host.
    The existing placement test only checks SOME accumulator landed
    there eagerly; this pins the round-trip on a to_static-captured
    step, where the post-step host pin rides the program's output
    shardings."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.sharding import (
        GroupShardedOptimizerStage2)
    from paddle_tpu.jit import to_static

    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=lin.parameters())
    sharded = GroupShardedOptimizerStage2(lin.parameters(), opt,
                                          offload=True)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))

    def train_step(xb):
        loss = (lin(xb) * lin(xb)).sum()
        loss.backward()
        sharded.step()
        sharded.clear_grad()
        return loss

    step = to_static(train_step)
    for _ in range(2):
        step(x)
    mks = {getattr(a.sharding, "memory_kind", None)
           for accs in opt._accumulators.values()
           for a in accs.values() if hasattr(a, "sharding")}
    assert mks == {"pinned_host"}, mks
