"""Version compatibility for the moving jax API surface.

The repo targets the modern spelling (`jax.shard_map`, `jax.lax.pvary`);
older jaxlibs (this container ships 0.4.x) keep the same machinery under
`jax.experimental.shard_map` with `check_rep` instead of `check_vma` and
have no replication-typing ops at all.  Routing every internal use
through this module keeps the subsystems (ring attention, reshard,
hybrid/pipeline steps) importable and runnable on both generations.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "pvary", "axis_size", "distributed_is_initialized"]


def distributed_is_initialized() -> bool:
    """`jax.distributed.is_initialized()` when present (jax >= 0.4.34-ish);
    older jaxlibs expose the same fact as the private global state's
    client handle."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - exotic jax builds
        return False


def axis_size(axis_name):
    """`jax.lax.axis_size` when present; else the classic `psum(1, axis)`
    idiom (constant-folded to the static mesh-axis size under tracing)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
    """`jax.shard_map` when present, else the experimental spelling with
    `check_vma` mapped onto `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kw)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, **kw)


def pvary(x, axes):
    """Mark a (pytree of) rank-invariant value(s) as varying over `axes`.

    New jax tracks varying-mesh-axes types and needs the cast for scan
    carries whose updates are rank-dependent; pre-vma jax doesn't type
    replication, so the identity is correct there."""
    lax = jax.lax
    if hasattr(lax, "pcast"):
        cast = lambda v: lax.pcast(v, axes, to="varying")  # noqa: E731
    elif hasattr(lax, "pvary"):
        cast = lambda v: lax.pvary(v, axes)  # noqa: E731
    else:
        return x
    return jax.tree_util.tree_map(cast, x)
