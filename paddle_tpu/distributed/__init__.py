"""paddle_tpu.distributed — built up across collective/fleet/auto_parallel.
Parity target: `python/paddle/distributed/`."""

from . import env  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
