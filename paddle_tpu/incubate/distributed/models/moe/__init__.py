"""Mixture-of-experts.  Parity: `python/paddle/incubate/distributed/models/moe/`."""

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate, capacity
from .moe_layer import ExpertMLP, MoELayer

__all__ = ["MoELayer", "ExpertMLP", "BaseGate", "NaiveGate", "SwitchGate",
           "GShardGate", "capacity"]
