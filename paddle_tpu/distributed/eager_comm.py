"""Eager cross-process collectives on global arrays.

Role of the reference's eager ProcessGroup
(`paddle/fluid/distributed/collective/process_group.h:47`,
`process_group_nccl.cc` — every rank calls `all_reduce(tensor)` and NCCL
moves the bytes): in a multi-process JAX job the equivalent is a tiny
cached jitted program over a one-device-per-process mesh:

1. each process wraps its local value as its shard of a global
   [W, *shape] array (`jax.make_array_from_single_device_arrays`);
2. all processes enter the SAME cached compiled program in lockstep (an
   eager collective call is already a lockstep point — identical to a
   NCCL kernel launch);
3. the program is a `shard_map` over the one-device-per-process mesh
   whose body is the matching `lax` collective (psum / psum_scatter /
   all_gather / all_to_all), and each process reads back its
   addressable shard.

The shard_map formulation keeps per-process peak memory at
O(shape/W) + O(shape): nothing ever materializes the W x shape stack on
one device (the previous jit-with-replicated-output lowering
all-gathered the stacked array before reducing, so a W-process
reduce_scatter peaked at W x shape per process).  all_gather's output
IS W x shape — that one is inherent to its contract.

Programs cache per (op, ndim, group) and jit retraces per shape/dtype —
after the first call a collective is one executable launch, the same
cost model as a cached NCCL plan.  These paths are for EAGER tensors
between jit regions (DDP grad sync, metric reduction); code inside
shard_map/jit keeps using the axis-context lowering in `collective.py`.

Granularity contract: the eager collective's participation unit is the
PROCESS (one contribution per rank), exactly the reference's
one-rank-per-GPU model.  A process that owns several local devices
(e.g. a virtual 8-device CPU mesh) has no well-defined "its tensor" —
calls in that topology raise instead of silently reducing only device
0's value; put the collective inside jit/shard_map (axis context) or
launch one process per device.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.jax_compat import shard_map as _shard_map

_AXIS = "world"


def in_multiprocess() -> bool:
    return jax.process_count() > 1


def group_size(group) -> int:
    """Number of PARTICIPATING PROCESSES (the eager collective's world;
    a process may own many local devices — e.g. a virtual 8-device CPU
    mesh — but contributes one row)."""
    ranks = group_ranks(group)
    return len(ranks) if ranks is not None else jax.process_count()


def group_ranks(group) -> Optional[Sequence[int]]:
    """Process ids participating; None = every process."""
    if group is None or getattr(group, "_ranks", None) is None:
        return None
    return tuple(group._ranks)


@functools.lru_cache(maxsize=None)
def _group_mesh(ranks: Optional[tuple]) -> Mesh:
    """1-D mesh with ONE device per participating process (a process may
    own several local devices; the collective's unit is the process, as in
    the reference's one-rank-per-GPU model)."""
    per_proc = {}
    for d in jax.devices():
        if ranks is None or d.process_index in ranks:
            cur = per_proc.get(d.process_index)
            if cur is None or d.id < cur.id:
                per_proc[d.process_index] = d
    devs = [per_proc[p] for p in sorted(per_proc)]
    return Mesh(np.array(devs), (_AXIS,))


def row_of(group, global_rank: int) -> int:
    """Row of a GLOBAL process rank in the stacked [W, *shape] layout
    (mesh rows are the group's process ids in sorted order)."""
    ranks = group_ranks(group)
    if ranks is None:
        return global_rank
    return sorted(ranks).index(global_rank)


def my_row(group=None) -> int:
    """This process's row in the stacked [W, *shape] layout."""
    return row_of(group, jax.process_index())


def _stack(mesh: Mesh, value: jax.Array) -> jax.Array:
    """Local [*s] -> global [W, *s], row w owned by process w.

    Assembled from the existing device buffer
    (make_array_from_single_device_arrays) — no host round trip; a DDP
    reducer hook's per-parameter collective stays device-side."""
    sharding = NamedSharding(mesh, P(_AXIS, *([None] * value.ndim)))
    mine = [d for d in mesh.devices.flat
            if d.process_index == jax.process_index()]
    local = jax.device_put(jnp.asarray(value)[None], mine[0])
    W = mesh.devices.size
    return jax.make_array_from_single_device_arrays(
        (W,) + tuple(value.shape), sharding, [local])


def _local_view(garr: jax.Array) -> jax.Array:
    """The replicated result's addressable shard (no host round trip)."""
    return garr.addressable_shards[0].data


def _check_process_granular(op_name: str) -> None:
    """Hard error for the undefined topology (VERDICT r5 #8): eager
    collectives are PROCESS-granular — with several local devices there
    is no single "this process's tensor" to contribute, and the
    one-device-per-process mesh would silently drop the rest."""
    if jax.local_device_count() > 1:
        raise RuntimeError(
            f"eager {op_name}: this process owns "
            f"{jax.local_device_count()} local devices, but eager "
            "cross-process collectives are process-granular (one "
            "contribution per process).  Run the collective inside "
            "jit/shard_map with a mesh axis (distributed/collective.py "
            "axis contexts), or launch one process per device.")


# Per-device bodies: local input is this process's [1, *s] block of the
# stacked array; every body stays O(local) except all_gather, whose
# OUTPUT is the [W, *s] stack the caller asked for.
_REDUCERS = {
    "sum": lambda x: jax.lax.psum(x[0], _AXIS),
    "avg": lambda x: jax.lax.pmean(x[0], _AXIS),
    "mean": lambda x: jax.lax.pmean(x[0], _AXIS),
    "max": lambda x: jax.lax.pmax(x[0], _AXIS),
    "min": lambda x: jax.lax.pmin(x[0], _AXIS),
    # no pprod primitive: gather W local values, reduce locally (W x s
    # peak, but prod is not on any gradient hot path)
    "prod": lambda x: jnp.prod(jax.lax.all_gather(x[0], _AXIS), axis=0),
}


@functools.lru_cache(maxsize=None)
def _program(kind: str, ranks: Optional[tuple], ndim: int,
             arg: Optional[int] = None):
    """Cached compiled collective: global [W, *s] in (each process holds
    its own row), shard_map body = the matching lax collective, so peak
    per-process memory is O(s/W)+O(s) — never the W x s stack."""
    mesh = _group_mesh(ranks)
    in_spec = P(_AXIS, *([None] * ndim))
    out_spec = P()                       # replicated result (default)

    if kind in _REDUCERS:
        fn = _REDUCERS[kind]
    elif kind == "broadcast":
        def fn(x):                       # select-and-psum: O(s), no stack
            mine = jax.lax.axis_index(_AXIS) == arg
            out = jax.lax.psum(
                jnp.where(mine, x[0], jnp.zeros_like(x[0])), _AXIS)
            # psum widens bool to int32; only the src row contributed,
            # so casting back is exact for every dtype
            return out.astype(x.dtype)
    elif kind == "all_gather":
        fn = lambda x: jax.lax.all_gather(x[0], _AXIS)   # noqa: E731
    elif kind == "reduce_scatter":
        # [W*m, ...] per process -> this process's summed [m, ...] row
        # block, O(s/W) output with no replicated intermediate
        def fn(x):
            return jax.lax.psum_scatter(
                x[0], _AXIS, scatter_dimension=0, tiled=True)[None]
        out_spec = P(_AXIS, *([None] * ndim))
    elif kind == "alltoall":
        # [W, ...] per process, row r bound for rank r -> received stack
        def fn(x):
            return jax.lax.all_to_all(
                x[0], _AXIS, split_axis=0, concat_axis=0, tiled=True)[None]
        out_spec = P(_AXIS, *([None] * ndim))
    else:  # pragma: no cover
        raise ValueError(kind)
    body = _shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                      out_specs=out_spec, check_vma=False)
    return jax.jit(body)


def all_reduce(value: jax.Array, op: str = "sum", group=None) -> jax.Array:
    _check_process_granular("all_reduce")
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program(op, ranks, value.ndim)(g))


def broadcast(value: jax.Array, src_row: int, group=None) -> jax.Array:
    _check_process_granular("broadcast")
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program("broadcast", ranks, value.ndim,
                                src_row)(g))


def all_gather(value: jax.Array, group=None) -> jax.Array:
    """Returns the stacked [W, *shape] result (callers split/reshape)."""
    _check_process_granular("all_gather")
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program("all_gather", ranks, value.ndim)(g))


def reduce_scatter(value: jax.Array, op: str = "sum", group=None):
    """value [W*m, ...] per rank; returns this rank's [m, ...] of the
    summed result.  Only sum (the DDP/ZeRO op) is defined, as in the
    reference's reduce-scatter use.  Peak memory is ~one extra copy of
    `value` (the on-device stack row) plus the [m, ...] output — the
    psum_scatter body never forms the W x shape stack."""
    if op not in ("sum", "avg", "mean"):
        raise ValueError("reduce_scatter supports sum/avg")
    _check_process_granular("reduce_scatter")
    ranks = group_ranks(group)
    mesh = _group_mesh(ranks)
    g = _stack(mesh, value)
    out = _local_view(_program("reduce_scatter", ranks, value.ndim)(g))[0]
    if op in ("avg", "mean"):
        out = out / mesh.devices.size
    return out


def alltoall(value: jax.Array, group=None) -> jax.Array:
    """value [W, ...] per rank (row r bound for rank r); returns this
    rank's received [W, ...] stack."""
    _check_process_granular("alltoall")
    ranks = group_ranks(group)
    mesh = _group_mesh(ranks)
    g = _stack(mesh, value)
    return _local_view(_program("alltoall", ranks, value.ndim)(g))[0]
