"""Eager Tensor.

TPU-native analogue of the reference's eager ``paddle.Tensor``
(C++ `paddle/fluid/pybind/eager.cc` + `eager_method.cc`, phi DenseTensor
`paddle/phi/core/dense_tensor.h:37`, AutogradMeta
`paddle/fluid/eager/autograd_meta.h:61`).  The storage is a ``jax.Array``
(PJRT buffer) — or a JAX tracer during jit capture, which is what lets the
whole eager API be traced into one XLA program.

Paddle semantics preserved:
* ``stop_gradient`` defaults to True; ``Parameter`` defaults to False.
* ``.backward()`` runs the tape engine (framework/autograd_engine.py).
* ``.grad`` is itself a Tensor.
Operator overloads and most methods are monkey-patched from paddle_tpu.ops
(mirroring `python/paddle/base/dygraph/tensor_patch_methods.py`).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from . import autograd_engine as _engine
from .dygraph import is_grad_enabled

__all__ = ["Tensor", "Parameter", "to_tensor", "is_tensor"]


def _coerce_value(data, dtype=None, place=None):
    if isinstance(data, Tensor):
        val = data._value
    elif isinstance(data, (jax.Array,)) or hasattr(data, "aval"):
        # jax array or tracer
        val = data
    else:
        if dtype is None and isinstance(data, (list, tuple, int, float)):
            probe = np.asarray(data)
            if probe.dtype == np.float64:
                dtype = _dtypes.get_default_dtype()
            elif probe.dtype == np.int64:
                dtype = np.int64
        val = jnp.asarray(data, dtype=_dtypes.convert_dtype(dtype) if dtype else None)
        dtype = None  # already applied
    if dtype is not None:
        d = _dtypes.convert_dtype(dtype)
        if val.dtype != d:
            val = val.astype(d)
    if place is not None and isinstance(val, jax.Array):
        val = jax.device_put(val, place.jax_device)
    return val


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_grad_node", "_output_slot",
                 "_accum_node", "_leaf_hooks", "name", "persistable", "trainable",
                 "_dist_attr", "__weakref__")

    def __init__(self, data=None, dtype=None, place=None, stop_gradient: bool = True,
                 name: Optional[str] = None):
        self._value = _coerce_value(data, dtype, place) if data is not None else None
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._grad_node: Optional[_engine.GradNode] = None
        self._output_slot: int = 0
        self._accum_node: Optional[_engine.GradAccumulationNode] = None
        self._leaf_hooks: List[Callable] = []
        self.name = name or f"tensor_{id(self):x}"
        self.persistable = False
        self.trainable = not stop_gradient
        self._dist_attr = None  # set by paddle_tpu.distributed for DistTensor

    # -- classmethod wrap: build from raw value without conversion ------------
    @classmethod
    def _wrap(cls, value, stop_gradient: bool = True) -> "Tensor":
        t = cls.__new__(cls)
        t._value = value
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = None
        t._output_slot = 0
        t._accum_node = None
        t._leaf_hooks = []
        t.name = f"tensor_{id(t):x}"
        t.persistable = False
        t.trainable = not stop_gradient
        t._dist_attr = None
        return t

    # ------------------------------------------------------------------ meta
    @property
    def shape(self) -> List[int]:
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self) -> int:
        return self._value.ndim

    ndimension = ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from ..core import device as _device
        if isinstance(self._value, jax.Array) and not self._is_traced():
            try:
                d = list(self._value.devices())[0]
                return _device.Place(_device._kind(d), d.id)
            except Exception:
                pass
        return _device.current_place()

    def _is_traced(self) -> bool:
        return not isinstance(self._value, jax.Array) or isinstance(
            self._value, jax.core.Tracer)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    # -------------------------------------------------------------- autograd
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad = None
        elif isinstance(value, Tensor):
            self._grad = value
        else:
            self._grad = Tensor._wrap(jnp.asarray(value))

    def _accumulate_grad(self, raw_grad):
        for hook in self._leaf_hooks:
            res = hook(Tensor._wrap(raw_grad))
            if res is not None:
                raw_grad = res._value if isinstance(res, Tensor) else res
        if raw_grad.dtype != self._value.dtype and jnp.issubdtype(
                self._value.dtype, jnp.floating):
            raw_grad = raw_grad.astype(self._value.dtype)
        # distributed invariant: grad layout follows the parameter layout
        # (the reference stores grads with the param's dist_attr)
        from jax.sharding import NamedSharding
        if (isinstance(raw_grad, jax.Array)
                and not isinstance(raw_grad, jax.core.Tracer)
                and isinstance(getattr(self._value, "sharding", None),
                               NamedSharding)
                and raw_grad.sharding != self._value.sharding):
            raw_grad = jax.device_put(raw_grad, self._value.sharding)
        if self._grad is None:
            self._grad = Tensor._wrap(raw_grad)
        else:
            self._grad._value = self._grad._value + raw_grad

    def _get_accum_node(self) -> _engine.GradAccumulationNode:
        if self._accum_node is None:
            self._accum_node = _engine.GradAccumulationNode(self)
        return self._accum_node

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """Run the autograd engine from this tensor.

        Reference: ``Tensor.backward`` →  ``core.eager.run_backward``
        (`python/paddle/base/dygraph/tensor_patch_methods.py:250,:335`).
        """
        if self.stop_gradient and self._grad_node is None:
            raise RuntimeError(
                "Tensor.backward() on a tensor with stop_gradient=True and no "
                "grad graph.")
        if grad_tensor is None:
            seed = jnp.ones(self._value.shape, self._value.dtype)
        else:
            seed = grad_tensor._value if isinstance(grad_tensor, Tensor) \
                else jnp.asarray(grad_tensor)
        _engine.run_backward([self], [seed], retain_graph=retain_graph)

    def register_hook(self, hook: Callable) -> "RemovableHandle":
        """Hook fires when this tensor's grad is computed; may return new grad."""
        if self._grad_node is None:
            self._leaf_hooks.append(hook)
            return RemovableHandle(self._leaf_hooks, hook)
        wrapped = _wrap_node_hook(hook)
        hooks = self._grad_node.grad_hooks[self._output_slot]
        hooks.append(wrapped)
        return RemovableHandle(hooks, wrapped)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor._wrap(self._value, stop_gradient=True)

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    # ------------------------------------------------------------- host sync
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self):
        from ..jit import sot as _sot
        return _sot.intercept("item", self, lambda: self._value.item())

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    # jax interop: lets jnp.* consume Tensors directly.
    def __jax_array__(self):
        return self._value

    # -------------------------------------------------------------- mutation
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        new = jnp.asarray(value)
        if tuple(new.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {new.shape} vs {self._value.shape}")
        self._value = new.astype(self._value.dtype)
        return self

    def copy_(self, other, blocking: bool = True):
        return self.set_value(other)

    def _to_place(self, place) -> "Tensor":
        val = jax.device_put(self._value, place.jax_device)
        t = Tensor._wrap(val, stop_gradient=self.stop_gradient)
        return t

    def cpu(self):
        from ..core.device import CPUPlace
        return self._to_place(CPUPlace())

    def to(self, *args, **kwargs):
        from ..core.device import Place
        dtype = kwargs.pop("dtype", None)
        device = kwargs.pop("device", None)
        for a in args:
            if isinstance(a, str) and (":" in a or a in ("cpu", "tpu", "gpu")):
                device = a
            elif isinstance(a, Place):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            from .. import ops
            out = ops.cast(out, dtype)
        if device is not None:
            if isinstance(device, str):
                kind, _, idx = device.partition(":")
                device = Place(kind, int(idx or 0))
            out = out._to_place(device)
        return out

    # ---------------------------------------------------------------- dunder
    def __repr__(self):
        sg = self.stop_gradient
        if self._is_traced():
            return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                    f"stop_gradient={sg}, traced)")
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={sg},\n       {np.asarray(self._value)!r})")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __bool__(self):
        # concretizations route through the SOT hook: under guarded
        # capture (jit/sot.py) a traced value burns the recorded branch
        # and emits a guard instead of raising ConcretizationTypeError
        from ..jit import sot as _sot
        return _sot.intercept("bool", self, lambda: bool(self._value))

    def __int__(self):
        from ..jit import sot as _sot
        return _sot.intercept("int", self, lambda: int(self._value))

    def __float__(self):
        from ..jit import sot as _sot
        return _sot.intercept("float", self, lambda: float(self._value))

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __dlpack__(self, *a, **k):
        return self._value.__dlpack__(*a, **k)

    def __deepcopy__(self, memo):
        """Copy value + flags; the autograd graph is never copied (matches
        paddle: deepcopy of a mid-graph tensor detaches)."""
        cls = type(self)
        val = self._value
        if isinstance(val, jax.Array) and not self._is_traced():
            # a real buffer copy: the copy must survive the original being
            # donated by a jitted optimizer step (and vice versa)
            val = jnp.array(val, copy=True)
        t = cls._wrap(val, stop_gradient=self.stop_gradient)
        t.name = self.name  # stable identity: optimizer state keys by name
        t.persistable = self.persistable
        t.trainable = self.trainable
        if isinstance(self, Parameter):
            t.optimize_attr = dict(self.optimize_attr)
            t.need_clip = self.need_clip
        memo[id(self)] = t
        return t

    # Arithmetic/indexing dunders are patched in paddle_tpu/ops/__init__.py.


class RemovableHandle:
    def __init__(self, hooks_list, entry):
        self._list = hooks_list
        self._entry = entry

    def remove(self):
        try:
            self._list.remove(self._entry)
        except ValueError:
            pass


def _wrap_node_hook(user_hook):
    def node_hook(raw_grad):
        if raw_grad is None:
            return None
        res = user_hook(Tensor._wrap(raw_grad))
        if res is None:
            return None
        return res._value if isinstance(res, Tensor) else res
    return node_hook


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable, optimizer-visible.

    Reference: `python/paddle/base/framework.py` EagerParamBase.
    """
    __slots__ = ("optimize_attr", "regularizer", "is_distributed",
                 "need_clip", "_asp_mask")

    _name_counter = 0

    def __init__(self, data=None, dtype=None, name=None, trainable: bool = True):
        if name is None:
            # deterministic creation-order name (reference EagerParamBase
            # auto-names via a global unique_name counter) so optimizer
            # checkpoints keyed by param name are stable across processes
            name = f"param_{Parameter._name_counter}"
            Parameter._name_counter += 1
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True

    @classmethod
    def _wrap(cls, value, stop_gradient: bool = False):
        t = super()._wrap.__func__(cls, value, stop_gradient)
        t.name = f"param_{Parameter._name_counter}"
        Parameter._name_counter += 1
        t.persistable = True
        t.optimize_attr = {"learning_rate": 1.0}
        t.regularizer = None
        t.is_distributed = False
        t.need_clip = True
        return t


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor equivalent."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)
