"""Analytic cost + memory models for the auto-tuner.

Parity: `python/paddle/distributed/auto_tuner/cost_model.py` and
`prune.py`'s memory estimation — the reference ranks hybrid-parallel
candidates with a roofline-style time model and prunes by estimated HBM
before paying for real trials.

First-order TPU model (the scaling-book recipe): per-device step time =
compute (model FLOPs / peak, derated by an efficiency factor) + exposed
communication (DP gradient all-reduce + TP activation collectives over
ICI) all scaled by the pipeline bubble (M + pp - 1) / M.  It exists to
ORDER candidates and prune impossible ones — absolute seconds are not
the contract, the ranking is.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ...observability.flops import training_flops_per_token
from .tuner import Trial

__all__ = ["ModelSpec", "Hardware", "estimate_params", "estimate_memory",
           "estimate_step_time", "rank_candidates", "prune_by_model"]


@dataclass
class ModelSpec:
    """Transformer shape the tuner is searching a layout for."""
    num_layers: int
    hidden_size: int
    num_heads: int
    vocab_size: int
    seq_len: int
    global_batch_size: int
    intermediate_size: int = 0

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


@dataclass
class Hardware:
    """Per-chip capability (defaults: TPU v5e public specs)."""
    peak_flops: float = 197e12        # bf16
    hbm_bytes: float = 16 * 2 ** 30
    ici_bandwidth: float = 45e9       # bytes/s per link direction
    mfu_ceiling: float = 0.5          # achievable fraction of peak


def estimate_params(spec: ModelSpec) -> int:
    """Dense decoder parameter count (QKV+proj+MLP+embeddings)."""
    h, i = spec.hidden_size, spec.intermediate_size
    per_layer = 4 * h * h + 2 * h * i + 4 * h  # attn + mlp + norms
    return spec.num_layers * per_layer + spec.vocab_size * h \
        + spec.seq_len * h


def estimate_memory(trial: Trial, spec: ModelSpec,
                    weight_bytes: int = 2, state_bytes: int = 12,
                    act_bytes: int = 2) -> float:
    """Per-device HBM estimate: bf16 weights + grads sharded over mp*pp,
    fp32 Adam state (m + v + master = 12 B/param) additionally over the
    ZeRO 'sharding' axis, and one microbatch of remat'd activations per
    pipeline stage (~4 live tensors of [mbs, S, H] per layer)."""
    p = estimate_params(spec)
    model_shard = trial.mp * trial.pp
    weights = p * weight_bytes / model_shard
    grads = p * weight_bytes / model_shard
    opt = p * state_bytes / (model_shard * trial.sharding)
    acts = (4 * act_bytes * trial.micro_batch_size * spec.seq_len
            * spec.hidden_size * spec.num_layers / trial.pp)
    return weights + grads + opt + acts


def estimate_step_time(trial: Trial, spec: ModelSpec,
                       hw: Hardware = Hardware()) -> float:
    """First-order per-step seconds for one device."""
    p = estimate_params(spec)
    tokens = spec.global_batch_size * spec.seq_len
    data_ways = trial.dp * trial.sharding
    model_ways = trial.mp * trial.pp
    # per-token train FLOPs from the ONE shared accounting helper
    # (observability.flops) — the same 6N + 12LHS the models and bench
    # report MFU against, so tuner rankings and measured MFU agree
    fpt = training_flops_per_token(p, spec.num_layers, spec.hidden_size,
                                   spec.seq_len)
    flops_dev = fpt * tokens / (data_ways * model_ways)
    compute = flops_dev / (hw.peak_flops * hw.mfu_ceiling)

    # DP gradient all-reduce: ring 2(n-1)/n of the local grad bytes
    grad_bytes = 2.0 * p / model_ways
    n = data_ways
    comm_dp = 2 * grad_bytes * (n - 1) / max(n, 1) / hw.ici_bandwidth \
        if n > 1 else 0.0
    # TP: per layer ~4 collectives moving the activation block
    local_tokens = tokens / data_ways
    act_bytes = 2.0 * local_tokens * spec.hidden_size / trial.mp
    comm_mp = (4 * spec.num_layers / trial.pp) * act_bytes \
        * (trial.mp - 1) / max(trial.mp, 1) / hw.ici_bandwidth \
        if trial.mp > 1 else 0.0
    # PP: p2p activations are tiny; the cost is the bubble
    local_bs = spec.global_batch_size // max(data_ways, 1)
    m = max(local_bs // max(trial.micro_batch_size, 1), 1)
    bubble = (m + trial.pp - 1) / m
    return (compute + comm_dp + comm_mp) * bubble


def prune_by_model(trials: List[Trial], spec: ModelSpec,
                   hw: Hardware = Hardware(),
                   headroom: float = 0.9) -> List[Trial]:
    """Drop candidates whose estimated HBM exceeds `headroom` x capacity;
    records the estimate on the trial."""
    kept = []
    for t in trials:
        mem = estimate_memory(t, spec)
        t.extra["est_memory_bytes"] = mem
        if mem <= headroom * hw.hbm_bytes:
            kept.append(t)
    return kept


def rank_candidates(trials: List[Trial], spec: ModelSpec,
                    hw: Hardware = Hardware()) -> List[Trial]:
    """Order candidates by estimated step time (best first) — real trials
    then confirm in model-predicted order, so a trial budget cut loses
    the least-promising configs (the reference cost model's role)."""
    for t in trials:
        t.extra["est_step_seconds"] = estimate_step_time(t, spec, hw)
    return sorted(trials, key=lambda t: t.extra["est_step_seconds"])
