from . import datasets, models, transforms  # noqa: F401
from . import ops  # noqa: F401
