"""SPMD rule library + reshard engine with Partial semantics.

Mirrors the reference's `test/auto_parallel/spmd_rules/test_matmul_rule.py`
etc. (dims_mapping in/out assertions) plus value-level reshard checks on
the CPU mesh.
"""

import numpy as np
import pytest

import jax

from paddle_tpu.core.jax_compat import shard_map as compat_shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.auto_parallel import (DistAttr, PartialTensor,
                                                  infer_spmd, make_partial,
                                                  reshard_partial)
from paddle_tpu.distributed.auto_parallel.placement import (Partial,
                                                            Replicate, Shard)


# ------------------------------------------------------------------- rules
def test_matmul_rule_row_parallel():
    # x: [M/mesh0, K], y: [K, N] -> out [M/mesh0, N]
    ins, out = infer_spmd("matmul", DistAttr([0, -1]), DistAttr([-1, -1]))
    assert out == DistAttr([0, -1])


def test_matmul_rule_contraction_becomes_partial():
    # x: [M, K/mesh1], y: [K/mesh1, N] -> out [M, N] partial over mesh1
    ins, out = infer_spmd("matmul", DistAttr([-1, 1]), DistAttr([1, -1]))
    assert out.dims_mapping == [-1, -1]
    assert out.partial_dims == {1}


def test_matmul_rule_conflicting_shards_replicate():
    ins, out = infer_spmd("matmul", DistAttr([-1, 0]), DistAttr([1, -1]))
    # k mapped to both 0 and 1 -> conflict resolved; no crash
    assert out.ndim == 2


def test_matmul_rule_batched_and_transposed():
    # batched: [B/mesh0, M, K] @ [B/mesh0, K, N]
    ins, out = infer_spmd("matmul", DistAttr([0, -1, -1]),
                          DistAttr([0, -1, -1]))
    assert out == DistAttr([0, -1, -1])
    # trans_y: y is [N/mesh1, K]
    ins, out = infer_spmd("matmul", DistAttr([-1, -1]), DistAttr([1, -1]),
                          trans_y=True)
    assert out == DistAttr([-1, 1])


def test_elementwise_broadcast_merge():
    ins, out = infer_spmd("elementwise", DistAttr([0, -1]), DistAttr([-1]))
    assert out == DistAttr([0, -1])
    assert ins[1] == DistAttr([-1])
    ins, out = infer_spmd("elementwise", DistAttr([0, -1]), DistAttr([-1, 1]))
    assert out == DistAttr([0, 1])


def test_reduction_rule_partial():
    ins, out = infer_spmd("reduction", DistAttr([0, 1]), axis=1)
    assert out.dims_mapping == [0]
    assert out.partial_dims == {1}
    ins, out = infer_spmd("reduction", DistAttr([0, 1]), axis=1,
                          keep_dim=True)
    assert out.dims_mapping == [0, -1]
    # non-linear reductions (max) don't produce partials
    ins, out = infer_spmd("reduction", DistAttr([0, 1]), axis=1,
                          linear=False)
    assert out.partial_dims == set()


def test_reshape_rule_split_and_merge():
    # [B/mesh0, S*H] -> [B/mesh0, S, H]: shard follows leading group dim
    ins, out = infer_spmd("reshape", DistAttr([0, -1]),
                          src_shape=[8, 12], dst_shape=[8, 3, 4])
    assert out == DistAttr([0, -1, -1])
    # merge [B/mesh0, S, H] -> [B/mesh0, S*H]
    ins, out = infer_spmd("reshape", DistAttr([0, 1, -1]),
                          src_shape=[8, 3, 4], dst_shape=[8, 12])
    assert out == DistAttr([0, 1])


def test_transpose_embedding_softmax_rules():
    ins, out = infer_spmd("transpose", DistAttr([0, -1, 1]), perm=[2, 0, 1])
    assert out == DistAttr([1, 0, -1])

    ins, out = infer_spmd("embedding", DistAttr([0, -1]), DistAttr([1, -1]))
    assert out.dims_mapping == [0, -1, -1]
    assert out.partial_dims == {1}  # vocab-parallel partial

    ins, out = infer_spmd("softmax", DistAttr([0, 1]), axis=-1)
    assert out == DistAttr([0, -1])


def test_layer_norm_cross_entropy_concat_split_flash_rules():
    ins, out = infer_spmd("layer_norm", DistAttr([0, -1, 1]),
                          DistAttr([-1]), DistAttr([-1]),
                          begin_norm_axis=2)
    assert out == DistAttr([0, -1, -1])

    ins, out = infer_spmd("cross_entropy_with_softmax",
                          DistAttr([0, 1]), DistAttr([0]))
    assert out.dims_mapping == [0]
    assert out.partial_dims == {1}

    ins, out = infer_spmd("concat", [DistAttr([0, -1]), DistAttr([0, 1])],
                          axis=1)
    assert out == DistAttr([0, -1])

    ins, outs = infer_spmd("split", DistAttr([0, 1]), num=2, axis=1)
    assert all(o == DistAttr([0, -1]) for o in outs)

    # [B, S, H, D] layout: heads (dim 2) stay TP-sharded, seq must clear
    ins, out = infer_spmd("flash_attention", DistAttr([0, -1, 1, -1]),
                          DistAttr([0, -1, 1, -1]),
                          DistAttr([0, -1, 1, -1]))
    assert out == DistAttr([0, -1, 1, -1])
    ins, out = infer_spmd("flash_attention", DistAttr([0, 1, -1, -1]),
                          DistAttr([0, -1, -1, -1]),
                          DistAttr([0, -1, -1, -1]))
    assert out.dims_mapping[1] == -1  # sequence sharding cleared


def test_nonlinear_rules_force_partial_resolution():
    """softmax/layer_norm must demand p->r before running: inferred input
    clears partial (softmax of a partial sum is not a partial softmax)."""
    ins, out = infer_spmd("softmax", DistAttr([0, -1], partial_dims=[1]))
    assert ins[0].partial_dims == set()
    assert out.partial_dims == set()
    ins, out = infer_spmd("layer_norm", DistAttr([0, -1], partial_dims=[1]),
                          DistAttr([-1]), DistAttr([-1]))
    assert ins[0].partial_dims == set()


def test_concat_keeps_partials():
    ins, out = infer_spmd("concat",
                          [DistAttr([0, -1], partial_dims=[1]),
                           DistAttr([0, -1], partial_dims=[1])], axis=1)
    assert out.partial_dims == {1}


def test_flash_attention_no_double_mesh_dim():
    ins, out = infer_spmd("flash_attention", DistAttr([0, -1, -1, -1]),
                          DistAttr([-1, 0, -1, -1]),
                          DistAttr([-1, -1, -1, -1]))
    dms = [d for d in out.dims_mapping if d != -1]
    assert len(dms) == len(set(dms))  # each mesh dim at most once


def test_cross_entropy_merges_label_batch():
    ins, out = infer_spmd("cross_entropy_with_softmax",
                          DistAttr([-1, 1]), DistAttr([0]))
    # label batch shard merges into logits batch dim
    assert ins[0].dims_mapping[0] == 0
    assert ins[1].dims_mapping == [0]
    assert out.dims_mapping == [0]
    assert out.partial_dims == {1}


def test_mixed_partial_demands_resolution():
    """add(A_partial, B_full): the output must NOT be partial — B would be
    summed n times; the partial input's inferred attr drops the dim."""
    ins, out = infer_spmd("elementwise",
                          DistAttr([0, -1], partial_dims=[1]),
                          DistAttr([0, -1]))
    assert out.partial_dims == set()
    assert ins[0].partial_dims == set()
    # both partial: flows through
    ins, out = infer_spmd("elementwise",
                          DistAttr([0, -1], partial_dims=[1]),
                          DistAttr([0, -1], partial_dims=[1]))
    assert out.partial_dims == {1}
    # concat mixed
    ins, out = infer_spmd("concat",
                          [DistAttr([0, -1], partial_dims=[1]),
                           DistAttr([0, -1])], axis=1)
    assert out.partial_dims == set()


def test_nonlinear_reduction_clears_input_partial():
    ins, out = infer_spmd("reduction", DistAttr([0, -1], partial_dims=[1]),
                          axis=1, linear=False)
    assert ins[0].partial_dims == set()
    assert out.partial_dims == set()


def test_reshape_merged_group_forces_reshard_of_inner_shard():
    ins, out = infer_spmd("reshape", DistAttr([0, -1, 1]),
                          src_shape=[8, 3, 4], dst_shape=[8, 12])
    assert ins[0].dims_mapping == [0, -1, -1]  # inner shard must resolve
    assert out == DistAttr([0, -1])


def test_cross_entropy_hard_label_trailing_dim():
    ins, out = infer_spmd("cross_entropy_with_softmax",
                          DistAttr([0, 1]), DistAttr([0, -1]))
    assert ins[1].ndim == 2          # label keeps its rank
    assert ins[1].dims_mapping == [0, -1]
    assert out.dims_mapping == [0]
    assert out.partial_dims == {1}


def test_dist_reshard_api_still_callable():
    """The reshard submodule must not shadow the reshard() function."""
    import paddle_tpu.distributed as dist
    assert callable(dist.reshard)
    assert callable(dist.auto_parallel.reshard)


def test_make_partial_row_parallel_specs():
    mesh = _mesh(4)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    pt = make_partial(lambda xl, wl: xl @ wl, mesh, "mp", x, w,
                      in_specs=(P(None, "mp"), P("mp", None)))
    out = reshard_partial(pt, Replicate())
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(x @ w),
                               rtol=3e-5, atol=3e-5)


def test_unknown_rule_raises():
    with pytest.raises(KeyError):
        infer_spmd("no_such_op", DistAttr([-1]))


# ---------------------------------------------------------------- reshard
def _mesh(n=4, name="mp"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def test_partial_to_replicate_matches_full_matmul():
    """Row-parallel matmul -> PartialTensor -> p2r == serial result."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))   # [M, K]
    w = jnp.asarray(rng.randn(16, 4).astype(np.float32))   # [K, N]
    mesh = _mesh(4)
    # shard K over mp: each rank multiplies its K/4 slice -> partial sums
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "mp")))
    ws = jax.device_put(w, NamedSharding(mesh, P("mp", None)))

    def local_mm(x_loc, w_loc):
        return x_loc @ w_loc

    import functools

    @functools.partial(compat_shard_map, mesh=mesh,
                       in_specs=(P(None, "mp"), P("mp", None)),
                       out_specs=P("mp"))
    def partial_mm(xl, wl):
        return (xl @ wl)[None]

    pt = PartialTensor(partial_mm(xs, ws), mesh, "mp")
    out = reshard_partial(pt, Replicate())
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(x @ w),
                               rtol=2e-5, atol=1e-5)
    assert out._value.sharding.is_fully_replicated


def test_partial_to_shard_reduce_scatter():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    mesh = _mesh(4)

    import functools

    @functools.partial(compat_shard_map, mesh=mesh,
                       in_specs=(P(None, "mp"), P("mp", None)),
                       out_specs=P("mp"))
    def partial_mm(xl, wl):
        return (xl @ wl)[None]

    xs = jax.device_put(x, NamedSharding(mesh, P(None, "mp")))
    ws = jax.device_put(w, NamedSharding(mesh, P("mp", None)))
    pt = PartialTensor(partial_mm(xs, ws), mesh, "mp")
    out = reshard_partial(pt, Shard(0))
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(x @ w),
                               rtol=2e-5, atol=1e-5)
    spec = out._value.sharding.spec
    assert spec[0] == "mp"


def test_make_partial_helper():
    mesh = _mesh(4)
    a = jnp.arange(16, dtype=jnp.float32)  # sharded into 4 chunks of 4
    pt = make_partial(lambda chunk: chunk.sum(keepdims=True), mesh, "mp", a)
    assert isinstance(pt, PartialTensor)
    out = reshard_partial(pt, Replicate())
    assert float(np.asarray(out._value)[0]) == float(a.sum())


def test_shard_replicate_moves():
    from paddle_tpu.distributed.auto_parallel.reshard import get_reshard_fn
    mesh = _mesh(4)
    v = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    # r -> s
    vs = get_reshard_fn(Replicate(), Shard(0))(v, Shard(0), mesh=mesh,
                                               axis_name="mp")
    assert vs.sharding.spec[0] == "mp"
    # s -> s (axis move)
    vss = get_reshard_fn(Shard(0), Shard(1))(vs, Shard(1), mesh=mesh,
                                             axis_name="mp")
    assert vss.sharding.spec[1] == "mp"
    # s -> r
    vr = get_reshard_fn(Shard(1), Replicate())(vss, Replicate(), mesh=mesh,
                                               axis_name="mp")
    assert vr.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(v))


def test_cross_mesh_reshard():
    """Reshard between DIFFERENT meshes (reference `reshard/nd_mesh_...` +
    cross-mesh functions): device_put re-lays the array out on the target
    mesh; values survive any (mesh, placement) -> (mesh, placement) hop."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    mesh_a = dist.ProcessMesh(np.arange(8).reshape(4, 2),
                              dim_names=["dp", "mp"])
    mesh_b = dist.ProcessMesh(np.arange(8).reshape(2, 4),
                              dim_names=["x", "y"])
    t = dist.shard_tensor(paddle.to_tensor(x), mesh_a,
                          [dist.Shard(0), dist.Shard(1)])
    out = dist.reshard(t, mesh_b, [dist.Replicate(), dist.Shard(0)])
    np.testing.assert_array_equal(np.asarray(out._value), x)
    assert out._dist_attr["mesh"] is mesh_b
    # and back again with a different placement
    back = dist.reshard(out, mesh_a, [dist.Shard(1), dist.Replicate()])
    np.testing.assert_array_equal(np.asarray(back._value), x)


# ---------------------------------------------------------------- new rules
def _attr(*dm, partial=()):
    return DistAttr(list(dm), partial)


def test_squeeze_unsqueeze_rules():
    ins, out = infer_spmd("squeeze", _attr(0, -1, 1), axis=1)
    assert out.dims_mapping == [0, 1]
    ins, out = infer_spmd("unsqueeze", _attr(0, 1), axis=1)
    assert out.dims_mapping == [0, -1, 1]


def test_slice_stack_tile_rules():
    ins, out = infer_spmd("slice", _attr(0, 1), axes=[1])
    assert out.dims_mapping == [0, -1] and ins[0].dims_mapping == [0, -1]
    ins, out = infer_spmd("stack", [_attr(0, -1), _attr(-1, 1)], axis=0)
    assert out.dims_mapping == [-1, 0, 1]
    ins, out = infer_spmd("tile", _attr(0, 1), repeat_times=[1, 2])
    assert out.dims_mapping == [0, -1] and ins[0].dims_mapping == [0, -1]


def test_gather_scatter_rules():
    ins, out = infer_spmd("gather", _attr(0, 1), _attr(-1), axis=0)
    assert ins[0].dims_mapping == [-1, 1]
    assert out.dims_mapping == [-1, 1]
    ins, out = infer_spmd("scatter", _attr(0, 1), _attr(-1), _attr(-1, -1),
                          axis=0)
    assert ins[0].dims_mapping == [-1, 1]
    assert out.dims_mapping == [-1, 1]


def test_cumsum_dropout_rules_resolve_partial():
    ins, out = infer_spmd("cumsum", _attr(0, 1, partial=[2]), axis=1)
    assert out.dims_mapping == [0, -1] and not ins[0].partial_dims
    ins, out = infer_spmd("dropout", _attr(0, -1, partial=[1]))
    assert not ins[0].partial_dims and out.dims_mapping == [0, -1]


def test_rms_norm_fused_rope_rules():
    ins, out = infer_spmd("rms_norm", _attr(0, 1, 2), _attr(2),
                          begin_norm_axis=2)
    assert out.dims_mapping == [0, 1, -1]
    assert ins[1].dims_mapping == [-1]
    ins, outs = infer_spmd("fused_rope", _attr(0, 1, 2, -1),
                           _attr(0, -1, 2, -1))
    assert outs[0].dims_mapping == [0, -1, 2, -1]
    assert outs[1].dims_mapping == [0, -1, 2, -1]


def test_topk_sort_argmax_rules():
    ins, outs = infer_spmd("topk", _attr(0, 1), k=2, axis=1)
    assert outs[0].dims_mapping == [0, -1]
    ins, out = infer_spmd("sort", _attr(0, 1), axis=0)
    assert out.dims_mapping == [-1, 1]
    ins, out = infer_spmd("argmax", _attr(0, 1), axis=1)
    assert out.dims_mapping == [0]


def test_pad_flip_roll_triu_rules():
    ins, out = infer_spmd("pad", _attr(0, 1), paddings=[0, 0, 1, 1])
    assert out.dims_mapping == [0, -1]
    ins, out = infer_spmd("flip", _attr(0, 1), axis=0)
    assert out.dims_mapping == [-1, 1]
    ins, out = infer_spmd("roll", _attr(0, 1), shifts=1, axis=1)
    assert out.dims_mapping == [0, -1]
    ins, out = infer_spmd("triu", _attr(0, 1, 2))
    assert out.dims_mapping == [0, -1, -1]


def test_optimizer_update_rules():
    ins, out = infer_spmd("adam", _attr(0, -1), _attr(-1, 1),
                          _attr(-1, -1), _attr(-1, -1))
    assert out.dims_mapping == [0, 1]
    assert all(i.dims_mapping == [0, 1] for i in ins)
    ins, out = infer_spmd("sgd", _attr(0), _attr(-1, ))
    assert out.dims_mapping == [0]


def test_where_one_hot_unbind_take_rules():
    ins, out = infer_spmd("where", _attr(0, -1), _attr(-1, 1), _attr(-1, -1))
    assert out.dims_mapping == [0, 1]
    ins, out = infer_spmd("one_hot", _attr(0, 1), num_classes=8)
    assert out.dims_mapping == [0, 1, -1]
    ins, out = infer_spmd("unbind", _attr(0, 1), axis=0)
    assert out.dims_mapping == [1]
    ins, out = infer_spmd("take_along_axis", _attr(0, 1), _attr(0, -1),
                          axis=1)
    assert out.dims_mapping == [0, -1]


# --------------------------------------------- property tests: rule vs GSPMD
def _gspmd_decision(fn, in_attrs, shapes, mesh_axes=("dp", "mp")):
    """Lay inputs out per the rule's INFERRED attrs, jit with no output
    constraint, and return the output dims_mapping GSPMD chose."""
    n = 4
    devs = np.array(jax.devices()[:n]).reshape(2, 2)
    mesh = Mesh(devs, mesh_axes)
    args = []
    for attr, shape in zip(in_attrs, shapes):
        spec = P(*[mesh_axes[d] if d != -1 else None
                   for d in attr.dims_mapping])
        x = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
        args.append(jax.device_put(x, NamedSharding(mesh, spec)))
    out = jax.jit(fn)(*args)
    spec = out.sharding.spec if hasattr(out.sharding, "spec") else ()
    got = []
    for i in range(out.ndim):
        ax = spec[i] if i < len(spec) else None
        got.append(-1 if ax is None else mesh_axes.index(ax))
    return got


@pytest.mark.parametrize("case", [
    ("transpose", lambda x: jnp.transpose(x, (1, 0)),
     [_attr(0, 1)], [(8, 8)], {"perm": (1, 0)}),
    ("unsqueeze", lambda x: x[:, None, :],
     [_attr(0, 1)], [(8, 8)], {"axis": 1}),
    ("squeeze", lambda x: x[:, 0, :],
     [_attr(0, -1, 1)], [(8, 1, 8)], {"axis": 1}),
    ("one_hot", lambda x: jax.nn.one_hot(x.astype(jnp.int32), 4),
     [_attr(0, 1)], [(8, 8)], {"num_classes": 4}),
])
def test_rule_matches_gspmd_decision(case):
    """The rule's predicted output placement must match XLA's actual
    propagation on the virtual mesh for shard-preserving ops."""
    name, fn, attrs, shapes, kw = case
    ins, out = infer_spmd(name, *attrs, **kw)
    got = _gspmd_decision(fn, ins if isinstance(ins, list) else [ins],
                          shapes)
    want = out.dims_mapping
    assert got == want, (name, got, want)


def test_elementwise_matches_gspmd():
    ins, out = infer_spmd("elementwise", _attr(0, -1), _attr(-1, 1))
    got = _gspmd_decision(lambda a, b: a + b, ins, [(8, 8), (8, 8)])
    assert got == out.dims_mapping


def test_reduction_partial_matches_gspmd_allreduce():
    """A linear reduction over a sharded axis: the rule says 'partial over
    that mesh dim'; GSPMD realizes it as an immediate all-reduce — the
    VALUES must equal the unsharded reduction."""
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    ins, out = infer_spmd("reduction", _attr(-1, 1), axis=1)
    assert out.partial_dims == {1}
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "mp"))
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(None, "mp")))
    got = jax.jit(lambda v: v.sum(1))(xs)
    np.testing.assert_allclose(np.asarray(got), x.sum(1))


def test_nd_mesh_reshard_decomposition():
    """N-D mesh reshard decomposes into per-axis steps (ref
    nd_mesh_reshard_function.cc): values survive any placement change."""
    from paddle_tpu.distributed.auto_parallel.reshard import nd_mesh_reshard
    from paddle_tpu.distributed.auto_parallel.placement import (
        Partial, Replicate, Shard)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("x", "y"))
    v = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    src = jax.device_put(v, NamedSharding(mesh, P("x", "y")))
    out = nd_mesh_reshard(src, mesh, [Shard(0), Shard(1)],
                          [Replicate(), Shard(0)])
    assert out.sharding.spec == P("y", None) or \
        tuple(out.sharding.spec) == ("y",)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(v))
    # partial-over-x resolves by psum before relayout
    half = jax.device_put(v / 2, NamedSharding(mesh, P(None, "y")))
    outp = nd_mesh_reshard(half, mesh, [Partial(), Shard(1)],
                           [Replicate(), Shard(1)])
    np.testing.assert_allclose(np.asarray(outp), np.asarray(v))
    # x->p is not materializable: explicit error, not silent wrongness
    with pytest.raises(NotImplementedError):
        nd_mesh_reshard(src, mesh, [Shard(0), Shard(1)],
                        [Partial(), Shard(1)])


def test_r_to_p_roundtrip():
    from paddle_tpu.distributed.auto_parallel import (
        PartialTensor, get_reshard_fn)
    from paddle_tpu.distributed.auto_parallel.placement import (
        Partial, Replicate)
    mesh = Mesh(np.array(jax.devices()[:4]), ("mp",))
    v = jnp.arange(8, dtype=jnp.float32)
    pt = get_reshard_fn(Replicate(), Partial())(
        v, Partial(), mesh=mesh, axis_name="mp")
    back = get_reshard_fn(Partial(), Replicate())(pt, Replicate())
    np.testing.assert_array_equal(np.asarray(back), np.asarray(v))
