"""Text datasets.

Parity: `python/paddle/text/datasets/` (UCIHousing, Imdb, Imikolov,
Movielens, Conll05st).  The reference downloads from paddle's CDN; this
environment has no egress, so every dataset takes `data_file=` pointing at
a local copy in the reference's format, and raises a clear error when
asked to download.
"""

from __future__ import annotations

import gzip
import os
import tarfile
from typing import Optional

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05st"]


def _need_file(data_file, name):
    if data_file is None or not os.path.exists(data_file):
        raise FileNotFoundError(
            f"{name}: automatic download is unavailable in this build "
            f"(no network egress); pass data_file= with a local copy in "
            "the reference's published format")
    return data_file


class UCIHousing(Dataset):
    """506x13 regression table (reference `uci_housing.py`): whitespace-
    separated floats, 14 columns, feature-normalized like the reference."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 download: bool = False):
        data_file = _need_file(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats, target = raw[:, :-1], raw[:, -1:]
        mn, mx, avg = feats.min(0), feats.max(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:n_train], target[:n_train]],
                                       axis=1)
        else:
            self.data = np.concatenate([feats[n_train:], target[n_train:]],
                                       axis=1)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        row = self.data[i]
        return row[:-1], row[-1:]


class Imdb(Dataset):
    """Sentiment-labelled movie reviews from the aclImdb tar layout
    (reference `imdb.py`): builds a frequency-cutoff vocab, returns
    (int64 ids, label)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, download: bool = False):
        data_file = _need_file(data_file, "Imdb")
        import collections
        import re
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = collections.Counter()
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower().split()
                docs.append(text)
                labels.append(0 if m.group(1) == "pos" else 1)
                freq.update(text)
        vocab = {w: i for i, (w, c) in enumerate(
            sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))
            if c >= cutoff}
        unk = len(vocab)
        self.word_idx = vocab
        self.docs = [np.array([vocab.get(w, unk) for w in d], np.int64)
                     for d in docs]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """PTB n-gram dataset (reference `imikolov.py`)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50, download: bool = False):
        data_file = _need_file(data_file, "Imikolov")
        import collections
        split = "train" if mode == "train" else "valid"
        freq = collections.Counter()
        lines = []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                if member.name.endswith(f"ptb.{split}.txt"):
                    for line in tf.extractfile(member).read().decode() \
                            .splitlines():
                        words = line.strip().split()
                        lines.append(words)
                        freq.update(words)
        vocab = {w: i for i, (w, c) in enumerate(
            sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))
            if c >= min_word_freq}
        unk = len(vocab)
        self.word_idx = vocab
        self.data = []
        for words in lines:
            ids = [vocab.get(w, unk) for w in words]
            for j in range(len(ids) - window_size + 1):
                self.data.append(np.array(ids[j:j + window_size], np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Movielens(Dataset):
    """MovieLens-1M ratings (reference `movielens.py`)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0,
                 download: bool = False):
        data_file = _need_file(data_file, "Movielens")
        rows = []
        import zipfile
        with zipfile.ZipFile(data_file) as z:
            name = next(n for n in z.namelist() if n.endswith("ratings.dat"))
            for line in z.read(name).decode("latin1").splitlines():
                user, movie, rating, _ = line.strip().split("::")
                rows.append((int(user), int(movie), float(rating)))
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(rows)) < test_ratio
        keep = mask if mode == "test" else ~mask
        self.data = [r for r, k in zip(rows, keep) if k]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        u, m, r = self.data[i]
        return np.int64(u), np.int64(m), np.float32(r)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference `conll05.py`) — local-file only."""

    def __init__(self, data_file: Optional[str] = None, download=False,
                 **kwargs):
        _need_file(data_file, "Conll05st")
        raise NotImplementedError(
            "Conll05st parsing: the reference's preprocessed pickle is "
            "proprietary-format; load it with paddle.load and wrap in a "
            "paddle.io.Dataset")


class WMT14(Dataset):
    """WMT14 EN-FR translation (reference `wmt14.py` format: a tar with
    `src.dict`/`trg.dict` vocab files + `{mode}/{mode}` members holding
    tab-separated sentence pairs).  Local-file only in this build.

    Yields (src_ids, trg_ids, trg_ids_next) with <s>/<e> framing and
    <unk> (id 2) for out-of-dict words, sequences over 80 tokens
    dropped — the published dataset contract.
    """

    _START, _END, _UNK_IDX = "<s>", "<e>", 2

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = -1, download: bool = False):
        import tarfile
        if mode not in ("train", "test", "gen"):
            raise ValueError(f"mode must be train/test/gen, got {mode!r}")
        if dict_size <= 0:
            raise ValueError("dict_size must be positive")
        data_file = _need_file(data_file, type(self).__name__)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file, "r") as tf:
            self.src_dict = self._vocab(tf, "src.dict", dict_size)
            self.trg_dict = self._vocab(tf, "trg.dict", dict_size)
            pair_members = [m for m in tf.getnames()
                            if m.endswith(f"{mode}/{mode}")]
            for member in pair_members:
                for raw in tf.extractfile(member):
                    parts = raw.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    self._add_pair(*parts)

    def _vocab(self, tf, suffix, size):
        import tarfile as _t
        names = [m for m in tf.getnames() if m.endswith(suffix)]
        if len(names) != 1:
            raise ValueError(f"archive needs exactly one *{suffix}")
        vocab = {}
        for i, raw in enumerate(tf.extractfile(names[0])):
            if i >= size:
                break
            vocab[raw.decode().strip()] = i
        return vocab

    def _add_pair(self, src_seq, trg_seq):
        sd, td = self.src_dict, self.trg_dict
        u = self._UNK_IDX
        src = [sd.get(w, u) for w in
               [self._START] + src_seq.split() + [self._END]]
        trg = [td.get(w, u) for w in trg_seq.split()]
        if len(src) > 80 or len(trg) > 80:
            return
        self.src_ids.append(src)
        self.trg_ids.append([td[self._START]] + trg)
        self.trg_ids_next.append(trg + [td[self._END]])

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, i):
        return (np.asarray(self.src_ids[i], np.int64),
                np.asarray(self.trg_ids[i], np.int64),
                np.asarray(self.trg_ids_next[i], np.int64))


class WMT16(WMT14):
    """WMT16 Multi30K EN-DE (reference `wmt16.py` format: tar with
    `wmt16/{train,val,test}` tab-separated members and per-language
    vocab built on first use).  `lang` selects the source side."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = -1, trg_dict_size: int = -1,
                 lang: str = "en", download: bool = False):
        import tarfile
        if mode not in ("train", "test", "val"):
            raise ValueError(f"mode must be train/test/val, got {mode!r}")
        if src_dict_size <= 0 or trg_dict_size <= 0:
            raise ValueError("dict sizes must be positive")
        data_file = _need_file(data_file, "WMT16")
        src_col, trg_col = (0, 1) if lang == "en" else (1, 0)
        with tarfile.open(data_file, "r") as tf:
            members = [m for m in tf.getnames()
                       if m.endswith(f"wmt16/{mode}")]
            if not members:
                raise ValueError(f"archive has no wmt16/{mode} member")
            pairs = []
            for raw in tf.extractfile(members[0]):
                parts = raw.decode().strip().split("\t")
                if len(parts) == 2:
                    pairs.append((parts[src_col], parts[trg_col]))
        self.src_dict = self._build_vocab((p[0] for p in pairs),
                                          src_dict_size)
        self.trg_dict = self._build_vocab((p[1] for p in pairs),
                                          trg_dict_size)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for src_seq, trg_seq in pairs:
            self._add_pair(src_seq, trg_seq)

    def _build_vocab(self, seqs, size):
        from collections import Counter
        counts = Counter()
        for s in seqs:
            counts.update(s.split())
        vocab = {self._START: 0, self._END: 1, "<unk>": 2}
        for w, _ in counts.most_common(max(size - 3, 0)):
            vocab[w] = len(vocab)
        return vocab
