"""Ring attention: exact long-context attention over a sequence-parallel
mesh axis.

Parity target: the reference's long-context path is flash-attention +
sequence/context parallel groups (`fleet/utils/sequence_parallel_utils.py`,
`phi/kernels/gpu/flash_attn_kernel.cu` with cu_seqlens); this module is the
TPU-native equivalent SURVEY §5.7 calls out as "where TPU should beat the
reference": each device holds S/n of the sequence, K/V blocks rotate around
the ring via `ppermute` over ICI while every hop's partial attention is
accumulated with the flash-attention online-softmax update — compute and
communication overlap, no device ever materialises the full K/V.

Layout: (batch, num_heads, seq, head_dim), matching `ops/pallas_flash.py`.

Use inside `shard_map` (axis_name = the sequence/context-parallel mesh
axis), or call `ring_attention` with a mesh for the wrapped version.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention_local", "ring_attention",
           "ring_attention_chunked"]

_NEG = -1e30


def _register():
    from ....ops.registry import register_op
    register_op("ring_attention", _ring_attention_val)


def _block_update(q, k, v, acc, m, l, q_off, k_off, causal, scale):
    """One flash-attention online-softmax step on a (S_q, S_k) block."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jax.lax.iota(jnp.int32, q.shape[2])[:, None]
        kpos = k_off + jax.lax.iota(jnp.int32, k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))              # (B, H, Sq)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                   # (B, H, Sq, Sk)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype),
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha[..., None] + pv
    return acc_new, m_new, l_new


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """Exact attention where q/k/v are sequence-sharded over `axis_name`.

    Must run inside shard_map/pjit manual-sharding over `axis_name`.
    q, k, v: (B, H, S_local, D) — this rank's sequence slice.
    Returns (B, H, S_local, D) for this rank's queries over the FULL keys.
    """
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    if scale is None:
        scale = D ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    # initial carries are rank-invariant; outputs vary with the rank — mark
    # them varying over the manual axis so scan's carry types match
    if hasattr(jax.lax, "pcast"):
        acc0, m0, l0 = (jax.lax.pcast(x, (axis_name,), to="varying")
                        for x in (acc0, m0, l0))
    elif hasattr(jax.lax, "pvary"):
        acc0, m0, l0 = (jax.lax.pvary(x, (axis_name,))
                        for x in (acc0, m0, l0))

    def hop(carry, i):
        acc, m, l, k_cur, v_cur = carry
        # after i hops this rank holds the block that started on rank-i
        src = (rank - i) % n
        acc, m, l = _block_update(q, k_cur, v_cur, acc, m, l,
                                  q_off=rank * S, k_off=src * S,
                                  causal=causal, scale=scale)
        # rotate K/V one step around the ring (skipped after the last hop
        # would be ideal; keeping it uniform lets XLA pipeline the permute
        # of hop i+1 under the compute of hop i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_nxt, v_nxt), None

    (acc, m, l, _, _), _ = jax.lax.scan(
        hop, (acc0, m0, l0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def _ring_attention_val(q, k, v, mesh=None, axis_name="sp", causal=False,
                        scale=None):
    spec = P(None, None, axis_name, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec)
    def run(q, k, v):
        return ring_attention_local(q, k, v, axis_name, causal, scale)

    return run(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """Convenience wrapper: shard q/k/v's sequence dim over `axis_name` of
    `mesh` and run `ring_attention_local` under shard_map.

    Accepts paddle Tensors or jax arrays of shape (B, H, S, D) with S
    divisible by the axis size.  Returns the same type as the input.
    Tensor inputs go through the op registry, so eager `loss.backward()`
    differentiates through the ring (AD of ppermute is the reverse permute).
    """
    from ....framework.tensor import Tensor
    from ....ops.registry import dispatch as _dispatch

    static = {"mesh": mesh, "axis_name": axis_name, "causal": causal,
              "scale": scale}
    if isinstance(q, Tensor):
        return _dispatch("ring_attention", (q, k, v), static)
    return _ring_attention_val(q, k, v, **static)


_register()


def ring_attention_chunked(q, k, v, n_chunks: int, causal: bool = False,
                           scale: Optional[float] = None, q_off: int = 0):
    """Single-device form of one ring member: the SAME `_block_update`
    hop math, with the K/V rotation replaced by a `lax.scan` over the
    chunks (all resident).  q is this member's query slice (q_off = its
    absolute sequence offset, for the causal mask); k/v carry the FULL
    context.  Scores only ever materialize as (B, H, S_q, S_k/n) blocks —
    the memory shape that lets an n-device ring hold n× the context.

    q: (B, H, S_q, D); k, v: (B, H, S_k, D), S_k divisible by n_chunks.
    Exact (online softmax), matching the multi-device `ring_attention`
    hop-for-hop.
    """
    B, H, Sq, D = q.shape
    if scale is None:
        scale = D ** -0.5
    C = k.shape[2] // n_chunks
    kc = k.reshape(B, H, n_chunks, C, D)
    vc = v.reshape(B, H, n_chunks, C, D)

    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)

    def hop(carry, i):
        acc, m, l = carry
        acc, m, l = _block_update(
            q, kc[:, :, i], vc[:, :, i], acc, m, l,
            q_off=q_off, k_off=i * C, causal=causal, scale=scale)
        return (acc, m, l), None

    (acc, m, l), _ = jax.lax.scan(hop, (acc0, m0, l0),
                                  jnp.arange(n_chunks))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)
