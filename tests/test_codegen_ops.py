"""YAML single-source op codegen + the generated fft/math ops.

Mirrors the reference's generated-code discipline (ops.yaml is the truth;
generated artifacts must be in sync) and `test/legacy_test/test_fft.py`
(numpy parity).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import codegen


def test_generated_file_in_sync_with_yaml():
    with open(codegen.TARGET) as f:
        on_disk = f.read()
    assert on_disk == codegen.generate_source(), \
        "generated_ops.py is stale: run `python -m paddle_tpu.ops.codegen`"


def test_fft_family_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(16).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(np.asarray(paddle.fft.fft(t)._value),
                               np.fft.fft(x), atol=1e-4)
    np.testing.assert_allclose(np.asarray(paddle.fft.rfft(t)._value),
                               np.fft.rfft(x), atol=1e-4)
    # round trips
    back = paddle.fft.ifft(paddle.fft.fft(t))
    np.testing.assert_allclose(np.asarray(back._value).real, x, atol=1e-5)
    back_r = paddle.fft.irfft(paddle.fft.rfft(t), n=16)
    np.testing.assert_allclose(np.asarray(back_r._value), x, atol=1e-5)

    x2 = rng.randn(4, 8).astype(np.float32)
    t2 = paddle.to_tensor(x2)
    np.testing.assert_allclose(np.asarray(paddle.fft.fft2(t2)._value),
                               np.fft.fft2(x2), atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(paddle.fft.fftshift(t2)._value), np.fft.fftshift(x2))
    np.testing.assert_allclose(np.asarray(paddle.fft.fftfreq(8, 0.5)._value),
                               np.fft.fftfreq(8, 0.5).astype(np.float32))


def test_fft_norm_and_axis_args():
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(
        np.asarray(paddle.fft.fft(t, axis=0, norm="ortho")._value),
        np.fft.fft(x, axis=0, norm="ortho"), atol=1e-4)


def test_generated_math_ops():
    rng = np.random.RandomState(2)
    a = paddle.to_tensor(rng.randn(8).astype(np.float32))
    b = paddle.to_tensor(rng.randn(8).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(paddle.logaddexp(a, b)._value),
        np.logaddexp(np.asarray(a._value), np.asarray(b._value)), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(paddle.copysign(a, b)._value),
        np.copysign(np.asarray(a._value), np.asarray(b._value)))
    np.testing.assert_allclose(np.asarray(paddle.sinc(a)._value),
                               np.sinc(np.asarray(a._value)), rtol=1e-5)
    v = paddle.vander(a, n=4, increasing=True)
    np.testing.assert_allclose(
        np.asarray(v._value),
        np.vander(np.asarray(a._value), 4, increasing=True), rtol=1e-5)


def test_generated_ops_are_differentiable():
    """The codegen path must wire into the eager tape like any op."""
    from paddle_tpu.framework.tensor import Parameter
    p = Parameter(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
    spec = paddle.fft.rfft(p)
    power = paddle.sum(paddle.real(spec * paddle.conj(spec))) \
        if hasattr(paddle, "real") else paddle.sum(paddle.abs(spec) ** 2)
    power.backward()
    assert p.grad is not None
    # Parseval: d/dx sum|X|^2 = 2*N*x for rfft of real input (up to
    # half-spectrum bookkeeping); just require a nonzero finite gradient
    g = np.asarray(p.grad._value)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_codegen_cli_regenerates(tmp_path):
    out = tmp_path / "gen.py"
    codegen.write(str(out))
    assert out.read_text() == codegen.generate_source()
