"""Disaggregated prefill/decode handoff (ISSUE 16 tentpole b).

Prefill and decode want different hardware shapes: prefill is one big
compute-bound pass, decode is thousands of tiny bandwidth-bound ticks.
Running them on separate engine pools lets each pool batch its own kind
of work — but only if the KV the prefill engine just produced can move
to a decode engine without recompute.

:func:`hand_off` is that move, built entirely from the PR 15 export
bundle: the prefill engine serializes its prefix-cache index + block
KV (atomic, integrity-checked versions), the decode engine imports the
newest valid version and re-pins every entry through its own
``_alloc_block``.  Ownership is a **refcount transfer**, not a copy
that leaves two owners: the export side calls
:meth:`~...inference.serving.ServingEngine.release_exported_prefix` so
the serialized blocks return to its free pool, and blocksan verifies
the ledger on BOTH sides.  graft-lint rule R011 makes that pairing
structural — an export+import site that skips the release or the
verification fails lint, not production.

:class:`DisaggregatedPair` is the minimal two-pool topology: prefill
engine fills blocks (a 1-token generation caches the whole prompt),
the bundle moves, and the decode engine's own prefix hit turns the
"re-prefill" into a suffix-only step over already-adopted KV.  The
tier-1 test asserts the disaggregated greedy stream bit-matches the
single-engine stream.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from ...observability import flight_recorder as _flight
from ...observability import tracing as _tracing
from ...testing import jaxsan as _jaxsan
from ..serving import Request

__all__ = ["hand_off", "DisaggregatedPair"]


def hand_off(src, dst, root: str, trace_id: Optional[str] = None,
             parent_span: Optional[str] = None) -> dict:
    """Move prefix-cache KV ownership ``src`` -> ``dst`` via an export
    bundle under ``root``.  Returns a report:

    ``{"exported": {...}, "released_blocks": n, "imported": {...}}``

    The three legs are ordered so no moment has zero owners of live
    bytes and no steady state has two: export serializes while src
    still owns the blocks; release drops src's index pins (blocks a
    running src request still references stay put — releasing them
    would free KV under a live slot); import re-pins everything in
    dst's own refcount ledger.  blocksan verifies both sides.

    ``trace_id``/``parent_span`` thread the caller's trace context so
    the export leg (on src's flight recorder) and the import leg (on
    dst's) land in the same ``dump --fleet-trace`` timeline as the
    request that triggered the move.
    """
    ctx = {}
    if trace_id:
        ctx["trace_id"] = trace_id
        if parent_span:
            ctx["parent_span"] = parent_span
    t0 = time.time()
    exported = src.export_prefix_cache(root)
    released = src.release_exported_prefix()
    t1 = time.time()
    src._flightrec().record_span(
        "handoff_export", "handoff", t0, t1,
        blocks=int(exported.get("blocks", 0)),
        released=int(released), **ctx)
    dst._import_prefix_cache(root)
    _jaxsan.blocksan_verify(dst)
    report = {
        "exported": exported,
        "released_blocks": int(released),
        "imported": dict(dst._prefix_import_info or {}),
    }
    if trace_id:
        report["trace_id"] = trace_id
    dst._flightrec().record_span(
        "handoff_import", "handoff", t1, time.time(),
        blocks=int(report["imported"].get("blocks", 0) or 0), **ctx)
    _flight.default_recorder().record_event(
        "prefix_handoff",
        blocks=int(exported.get("blocks", 0)),
        released=int(released), **ctx)
    return report


class DisaggregatedPair:
    """A prefill engine + a decode engine joined by :func:`hand_off`.

    Both engines must be built from the same weights/config (the import
    fingerprint rejects mismatches).  ``root`` holds the handoff
    bundles; each :meth:`generate` writes a fresh export version under
    it and the decode side imports the newest."""

    def __init__(self, prefill_engine, decode_engine, root: str):
        self.prefill = prefill_engine
        self.decode = decode_engine
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.handoffs = 0
        self.last_report: Optional[dict] = None

    @staticmethod
    def _run(engine, req: Request, timeout_s: float = 120.0) -> None:
        engine.add_request(req)
        deadline = time.monotonic() + timeout_s
        while not req.done:
            if not engine.step():
                break
            if time.monotonic() > deadline:
                raise TimeoutError("disaggregated request timed out")

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 **req_kw) -> List[int]:
        """Prefill on one engine, decode on the other.

        The prefill leg is a ``max_new_tokens=1`` generation: admission
        runs the full-prompt prefill, caches every complete block in
        the prefix cache, and stops.  After the handoff the decode
        engine's admission sees a prefix hit over the adopted blocks,
        prefills only the uncached suffix, and decodes the stream.
        Returns the decode engine's ``output_ids`` (greedy streams
        bit-match the single-engine run)."""
        ids = [int(t) for t in prompt_ids]
        # One trace id covers all three legs (prefill, handoff, decode)
        # so the fleet trace shows the whole disaggregated lifecycle as
        # a single distributed request.
        trace_id = req_kw.pop("trace_id", None) or _tracing.mint_trace_id()
        span = _tracing.new_span_id()
        pre = Request(ids, max_new_tokens=1, trace_id=trace_id,
                      parent_span=span, **req_kw)
        self._run(self.prefill, pre)
        if pre.outcome not in (None, "finished"):
            raise RuntimeError(
                f"prefill leg ended '{pre.outcome}' (rid={pre.rid})")
        self.last_report = hand_off(self.prefill, self.decode, self.root,
                                    trace_id=trace_id, parent_span=span)
        self.handoffs += 1
        dec = Request(ids, max_new_tokens=max_new_tokens,
                      trace_id=trace_id, parent_span=span, **req_kw)
        self._run(self.decode, dec)
        if dec.outcome not in (None, "finished"):
            raise RuntimeError(
                f"decode leg ended '{dec.outcome}' (rid={dec.rid})")
        return list(dec.output_ids)
