"""KL divergence registry.

Parity: `python/paddle/distribution/kl.py` — kl_divergence (`:43`),
register_kl (`:75`), MRO-based dispatch (`:109`).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Type

import paddle_tpu as paddle
from .distribution import Distribution
from .distributions import (Bernoulli, Beta, Categorical, Dirichlet,
                            Exponential, Gamma, Laplace, Normal, Uniform)

__all__ = ["kl_divergence", "register_kl"]

_KL_REGISTRY: Dict[Tuple[Type, Type], Callable] = {}


def register_kl(cls_p: Type[Distribution], cls_q: Type[Distribution]):
    """Decorator registering a KL(p||q) rule for a distribution pair."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


def _dispatch(tp: Type, tq: Type) -> Callable:
    matches = []
    for (cp, cq), fn in _KL_REGISTRY.items():
        if issubclass(tp, cp) and issubclass(tq, cq):
            matches.append((tp.__mro__.index(cp) + tq.__mro__.index(cq), fn))
    if not matches:
        raise NotImplementedError(
            f"no KL(p||q) rule registered for ({tp.__name__}, "
            f"{tq.__name__}); use register_kl")
    return min(matches, key=lambda m: m[0])[1]


def kl_divergence(p: Distribution, q: Distribution):
    return _dispatch(type(p), type(q))(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1.0 - paddle.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # infinite when p's support leaves q's; assumes containment (reference
    # behavior)
    return paddle.log((q.high - q.low) / (p.high - p.low))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    a = paddle.clip(p.probs, 1e-7, 1 - 1e-7)
    b = paddle.clip(q.probs, 1e-7, 1 - 1e-7)
    return a * paddle.log(a / b) + (1 - a) * paddle.log((1 - a) / (1 - b))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    logp = p.logits - paddle.logsumexp(p.logits, axis=-1, keepdim=True)
    logq = q.logits - paddle.logsumexp(q.logits, axis=-1, keepdim=True)
    return paddle.sum(paddle.exp(logp) * (logp - logq), axis=-1)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
    ps, qs = pa + pb, qa + qb
    return (paddle.lgamma(qa) + paddle.lgamma(qb) - paddle.lgamma(qs)) \
        - (paddle.lgamma(pa) + paddle.lgamma(pb) - paddle.lgamma(ps)) \
        + (pa - qa) * paddle.digamma(pa) + (pb - qb) * paddle.digamma(pb) \
        + (qs - ps) * paddle.digamma(ps)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    pa, qa = p.concentration, q.concentration
    p0 = paddle.sum(pa, axis=-1)
    return paddle.lgamma(p0) - paddle.sum(paddle.lgamma(pa), axis=-1) \
        - paddle.lgamma(paddle.sum(qa, axis=-1)) \
        + paddle.sum(paddle.lgamma(qa), axis=-1) \
        + paddle.sum((pa - qa) * (paddle.digamma(pa)
                                  - paddle.digamma(p0)[..., None]), axis=-1)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    pc, pr, qc, qr = p.concentration, p.rate, q.concentration, q.rate
    return (pc - qc) * paddle.digamma(pc) - paddle.lgamma(pc) \
        + paddle.lgamma(qc) + qc * (paddle.log(pr) - paddle.log(qr)) \
        + pc * (qr / pr - 1.0)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    ratio = p.scale / q.scale
    diff = paddle.abs(p.loc - q.loc) / q.scale
    return -paddle.log(ratio) + ratio * paddle.exp(
        -paddle.abs(p.loc - q.loc) / p.scale) + diff - 1.0


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    ratio = q.rate / p.rate
    return paddle.log(p.rate) - paddle.log(q.rate) + ratio - 1.0


# ------------------------------------------------------- extras (extras.py)
from .extras import (Binomial, Cauchy, Independent,  # noqa: E402
                     MultivariateNormal)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    return p.kl_divergence(q)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    return p.kl_divergence(q)


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    # same total_count assumed (the reference's registry does too):
    # n * KL(Bernoulli(p) || Bernoulli(q))
    return p.total_count * (
        p.probs * (paddle.log(p.probs) - paddle.log(q.probs))
        + (1.0 - p.probs) * (paddle.log1p(-p.probs)
                             - paddle.log1p(-q.probs)))


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p._rank != q._rank:
        raise NotImplementedError(
            "KL between Independents of different reinterpreted ranks")
    inner = kl_divergence(p.base, q.base)
    if p._rank == 0:
        return inner
    return inner.sum(axis=list(range(inner.ndim - p._rank, inner.ndim)))
