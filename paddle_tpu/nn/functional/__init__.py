from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from ...ops.manipulation import one_hot  # noqa: F401


# ---- ops from the YAML single source ----
from paddle_tpu.ops.generated_ops import export_namespace as _exp  # noqa: E402
_exp(globals(), "nn_functional")
del _exp
