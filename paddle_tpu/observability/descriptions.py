"""Metric-description registry: the ONE name -> description map behind
the Prometheus exporter's ``# HELP`` lines (ISSUE 14 satellite).

Two sources, explicit wins:

* :func:`default` — every instrument created with a non-empty help
  string auto-registers it here (``metrics.Registry._get_or_create``),
  so the exporter and any future surface (docs generator, a /metrics
  index page) read descriptions from one place instead of each
  instrument object.
* :func:`describe` — an explicit operator/override registration, e.g.
  for derived series whose instrument help is empty or wrong.

The exporter emits ``# HELP`` only when :func:`lookup` returns text —
a metric with no description gets a bare ``# TYPE`` line, never a
malformed trailing-space HELP.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["describe", "default", "lookup", "known"]

_lock = threading.Lock()
_defaults: Dict[str, str] = {}
_overrides: Dict[str, str] = {}


def describe(name: str, text: str) -> None:
    """Explicitly register (or override) a metric's description."""
    with _lock:
        _overrides[name] = str(text)


def default(name: str, text: str) -> None:
    """Instrument-creation help; first registration wins (idempotent
    get-or-create instruments re-register on re-import)."""
    if not text:
        return
    with _lock:
        _defaults.setdefault(name, str(text))


def lookup(name: str) -> Optional[str]:
    with _lock:
        text = _overrides.get(name)
        if text is None:
            text = _defaults.get(name)
    return text or None


def known() -> Dict[str, str]:
    """Every described metric (defaults merged under overrides)."""
    with _lock:
        out = dict(_defaults)
        out.update(_overrides)
    return out
