"""Speculative decoding (`inference/speculative.py` + the serving
engine's spec tick path — ISSUE 10).

The losslessness contract under every composition the engine offers:
greedy streams BIT-identical to the plain engine (full-acceptance and
heavy-rejection drafts alike, under overlap, under TP degree 2, on the
prefix-cache hit path), seeded sampling distribution-preserving via
the standard rejection correction, and the prefix-cache immutability
invariant surviving rejected drafts.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt3_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft_same():
    """Same-weights draft: acceptance ~1.0, exercises the all-accept
    path and gives spec ticks that really emit k tokens."""
    paddle.seed(0)
    d = GPTForCausalLM(gpt3_tiny())
    d.eval()
    return d


@pytest.fixture(scope="module")
def draft_reject():
    """Unrelated tiny draft: near-zero acceptance, exercises the
    rejection/correction path on every tick."""
    paddle.seed(123)
    d = GPTForCausalLM(GPTConfig(vocab_size=1024, hidden_size=64,
                                 num_layers=1, num_heads=2,
                                 max_seq_len=256))
    d.eval()
    return d


def prompts():
    rng = np.random.RandomState(0)
    return (rng.randint(1, 1000, (12,)), rng.randint(1, 1000, (30,)),
            rng.randint(1, 1000, (7,)))


def _greedy_streams(model, specs, budgets, **engine_kw):
    eng = ServingEngine(model, max_batch=3, max_context=128,
                        block_size=16, **engine_kw)
    reqs = [eng.add_request(Request(p, max_new_tokens=b))
            for p, b in zip(specs, budgets)]
    eng.run()
    return eng, [list(r.output_ids) for r in reqs]


@pytest.mark.slow   # 11.3s measured (PR 14 re-budget): the EASY case —
                    # the rejecting-draft bit-parity pin (the hard
                    # case) stays in tier-1
def test_greedy_bit_identical_full_acceptance(model, draft_same):
    """THE losslessness headline: with an (ideal) always-agreeing
    draft, greedy streams match the plain engine token for token, the
    acceptance rate is 1.0, and the spec observability surface is
    populated (counters, stats, per-request trace)."""
    from paddle_tpu.observability import metrics as _metrics
    p1, p2, p3 = prompts()
    _, base = _greedy_streams(model, (p1, p2, p3), (10, 8, 12))
    _metrics.reset()
    eng, out = _greedy_streams(model, (p1, p2, p3), (10, 8, 12),
                               draft_model=draft_same, spec_decode=True,
                               spec_k=4)
    assert out == base
    st = eng.stats()["speculative"]
    assert st["spec_k"] == 4 and st["ticks"] > 0
    assert st["proposed_tokens"] > 0
    assert st["accept_rate"] == 1.0
    snap = _metrics.snapshot()
    prop = snap["serving.spec_proposed_tokens"]["series"][0]["value"]
    acc = snap["serving.spec_accepted_tokens"]["series"][0]["value"]
    assert prop == st["proposed_tokens"] and acc == st["accepted_tokens"]
    # per-request lifecycle trace carries the acceptance rate
    done = eng.finished
    assert all(r.trace["spec_accept_rate"] == 1.0 for r in done
               if r.trace is not None)
    # nothing leaked
    assert eng.stats()["free_blocks"] == eng.num_blocks
    assert eng.stats()["reserved"] == 0


@pytest.mark.slow  # 16s measured: adversarial-draft bit-parity compiles a second draft model; the accepting-draft twin keeps the fast bit-parity pin
def test_greedy_bit_identical_under_rejecting_draft(model, draft_reject):
    """Losslessness must NOT depend on the draft being any good: an
    unrelated draft rejects nearly everything and the streams are
    still bit-identical (every emitted token comes from the target
    logits), incl. an eos stream stopping at exactly the same token."""
    p1, p2, _ = prompts()
    _, base = _greedy_streams(model, (p1, p2), (12, 10))
    eng, out = _greedy_streams(model, (p1, p2), (12, 10),
                               draft_model=draft_reject,
                               spec_decode=True, spec_k=3)
    assert out == base
    st = eng.stats()["speculative"]
    assert st["ticks"] > 0 and st["accept_rate"] < 0.5
    # eos mid-stream: pick a later token of the plain stream as eos
    probe = base[0]
    eos = next((t for t in probe[1:] if t != probe[0]), None)
    assert eos is not None
    stop_at = probe.index(eos)
    eng2 = ServingEngine(model, max_batch=2, max_context=128,
                         block_size=16, draft_model=draft_reject,
                         spec_decode=True, spec_k=3)
    r = eng2.add_request(Request(p1, max_new_tokens=30,
                                 eos_token_id=eos))
    eng2.run()
    assert r.done and r.output_ids == probe[:stop_at + 1]
    assert eng2.stats()["free_blocks"] == eng2.num_blocks
    assert eng2.stats()["reserved"] == 0


@pytest.mark.slow   # compile-heavy composition pin; full runs cover it
def test_sampled_reproducible_and_overlap_parity(model, draft_reject):
    """Spec randomness is position-keyed: the sampled stream is a pure
    function of the request seed — identical across reruns and across
    the overlap flag (the double-buffered loop chains device handles;
    PR 3's parity contract extended to spec ticks)."""
    p1, p2, _ = prompts()

    def serve():
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, draft_model=draft_reject,
                            spec_decode=True, spec_k=3)
        g = eng.add_request(Request(p1, max_new_tokens=10))
        s = eng.add_request(Request(p2, max_new_tokens=10,
                                    do_sample=True, temperature=0.9,
                                    top_k=40, seed=7))
        eng.run()
        return eng, [list(g.output_ids), list(s.output_ids)]

    with flag_guard(serving_overlap=True):
        eng, first = serve()
        assert eng.stats()["speculative"]["ticks"] > 0
        _, again = serve()
    assert again == first
    with flag_guard(serving_overlap=False):
        _, sync = serve()
    assert sync == first


def test_accept_math_pins_emit_rule():
    """Unit pin of `accept_and_choose` on crafted logits: greedy rows
    emit ``1 + min(a, k-1)`` tokens — the accepted prefix plus one
    target-argmax token — and new_last is the final emitted token."""
    import jax.numpy as jnp
    from paddle_tpu.inference.speculative import accept_and_choose
    B, k, V = 1, 3, 8
    # target argmax chain at positions 0..2: tokens 5, 6, 7
    tl = np.full((B, k + 1, V), -10.0, np.float32)
    tl[0, 0, 5] = tl[0, 1, 6] = tl[0, 2, 7] = tl[0, 3, 1] = 0.0
    for dtoks, want_m, want_emit in (
            ([5, 6, 7], 3, [5, 6, 7]),    # all accepted, capped at k
            ([5, 6, 2], 3, [5, 6, 7]),    # reject at 2: correction = 7
            ([5, 2, 2], 2, [5, 6]),       # reject at 1
            ([2, 2, 2], 1, [5])):         # immediate reject
        chosen, m, a, new_last = accept_and_choose(
            jnp.asarray(tl), jnp.asarray([dtoks], jnp.int32),
            jnp.zeros((B, k, V), jnp.float32),
            jnp.zeros((B,), bool), jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32), jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.uint32), jnp.asarray([4], jnp.int32))
        assert int(m[0]) == want_m, dtoks
        assert list(np.asarray(chosen)[0][:want_m]) == want_emit, dtoks
        assert int(new_last[0]) == want_emit[-1], dtoks


def test_rejection_sampling_matches_target_distribution():
    """PR 3-style distribution match for the spec sampler: simulate N
    independent slots through the exact draft-draw + accept/correct
    pipeline the compiled program runs (same keys, same math) and
    compare the emitted FIRST token's frequencies with the target's
    filtered softmax — the Leviathan correction must leave the output
    distribution exactly p, even though draws come from q."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.speculative import (
        DRAFT_FOLD, _keys_at, accept_and_choose)
    from paddle_tpu.models.generation import (_process_logits,
                                              _process_logits_rows)
    rng = np.random.RandomState(5)
    V, k, N = 24, 2, 4000
    t_logits = (rng.randn(V) * 2).astype(np.float32)   # target
    # a REALISTIC draft approximates the target (that is why spec
    # decoding works at all): correlated logits give a mixed
    # accept/reject regime, exercising both paths of the correction
    d_logits = (t_logits + rng.randn(V).astype(np.float32) * 1.5)
    temp, top_k, top_p = 0.8, 12, 0.9
    # reference distribution: the host-filtered target softmax
    filtered = np.asarray(_process_logits(
        jnp.asarray(t_logits)[None], temp, top_k, top_p))[0]
    probs = np.exp(filtered - filtered.max())
    probs = probs / probs.sum()
    # N slots, one per seed, all at position base 16
    seeds = jnp.arange(N, dtype=jnp.uint32)
    lens = jnp.full((N,), 16, jnp.int32)
    do_sample = jnp.ones((N,), bool)
    tv = jnp.full((N,), temp, jnp.float32)
    kv = jnp.full((N,), top_k, jnp.int32)
    pv = jnp.full((N,), top_p, jnp.float32)
    # draft draws exactly as _draft_phase does (position = lens + j)
    dfilt = _process_logits_rows(
        jnp.asarray(np.tile(d_logits, (N, 1))), tv, kv, pv)
    dprob_row = jax.nn.softmax(dfilt, axis=-1)
    dtoks, dprobs = [], []
    for j in range(k):
        keys = _keys_at(seeds, lens + j, DRAFT_FOLD)
        dtoks.append(jax.vmap(jax.random.categorical)(keys, dfilt))
        dprobs.append(dprob_row)
    dtoks = jnp.stack(dtoks, axis=1).astype(jnp.int32)
    dprobs = jnp.stack(dprobs, axis=1)
    tlog = jnp.asarray(np.tile(t_logits, (N, k + 1, 1)))
    chosen, m, a, _ = accept_and_choose(
        tlog, dtoks, dprobs, do_sample, tv, kv, pv, seeds, lens)
    first = np.asarray(chosen)[:, 0]
    counts = np.bincount(first, minlength=V) / N
    assert counts[probs == 0].sum() == 0          # support respected
    np.testing.assert_allclose(counts, probs, atol=0.05)
    # sanity: both accept and reject paths really fired
    accepts_at_0 = np.asarray(dtoks)[:, 0] == first
    assert 0.05 < accepts_at_0.mean() < 0.95


@pytest.mark.slow   # compile-heavy composition pin; full runs cover it
def test_spec_tp2_greedy_bit_parity(model, draft_same):
    """Composition satellite: spec decode x tp_degree=2 on the
    8-virtual-device mesh — draft replicated, verify sharded — greedy
    streams bit-identical to the PLAIN degree-1 engine."""
    p1, p2, _ = prompts()
    _, base = _greedy_streams(model, (p1, p2), (8, 8))
    eng, out = _greedy_streams(model, (p1, p2), (8, 8), tp_degree=2,
                               draft_model=draft_same, spec_decode=True,
                               spec_k=3)
    assert out == base
    assert eng.stats()["speculative"]["ticks"] > 0
    assert eng.stats()["tp_degree"] == 2


@pytest.mark.slow   # compile-heavy composition pin; full runs cover it
def test_spec_prefix_cache_shared_blocks_stay_immutable(model,
                                                       draft_reject):
    """Composition satellite: on a prefix-cache hit, a spec tick's
    rejected drafts write (and roll back) ONLY in unregistered
    columns — the shared blocks' contents are byte-identical before
    and after, in the target AND draft pools, and the hit path's
    tokens bit-match a no-prefix engine."""
    rng = np.random.RandomState(3)
    sysp = list(rng.randint(1, 1000, (48,)))
    eng = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, draft_model=draft_reject,
                        spec_decode=True, spec_k=3, prefix_cache=True)
    r1 = eng.add_request(Request(sysp + [7], max_new_tokens=8))
    eng.run()
    match = eng.prefix.lookup(sysp + [9])
    blocks = list(match.blocks)
    assert blocks, "prefix must be resident after the first request"
    snap_t = [np.asarray(eng.pools[0][0][:, b]).copy() for b in blocks]
    snap_d = [np.asarray(eng.dpools[0][0][:, b]).copy() for b in blocks]
    hits0 = eng.prefix.hits
    r2 = eng.add_request(Request(sysp + [9], max_new_tokens=8))
    eng.run()
    assert eng.prefix.hits == hits0 + 1
    for b, s in zip(blocks, snap_t):
        np.testing.assert_array_equal(np.asarray(eng.pools[0][0][:, b]),
                                      s)
    for b, s in zip(blocks, snap_d):
        np.testing.assert_array_equal(np.asarray(eng.dpools[0][0][:, b]),
                                      s)
    off = ServingEngine(model, max_batch=2, max_context=128,
                        block_size=16, draft_model=draft_reject,
                        spec_decode=True, spec_k=3, prefix_cache=False)
    q = off.add_request(Request(sysp + [9], max_new_tokens=8))
    off.run()
    assert r2.output_ids == q.output_ids
    assert eng.stats()["free_blocks"] == eng.num_blocks


@pytest.mark.slow   # four engine builds (~13s); full runs cover it
def test_per_slot_eligibility_caps_instead_of_demoting(model,
                                                       draft_same):
    """ISSUE 13: eligibility is PER SLOT.  A short-budget request rides
    the spec tick with its own emit cap (`serving.spec_ineligible_slots`
    counts it) instead of demoting the whole batch to the plain path —
    and every stream, capped or not, is still bit-identical to the
    plain engine.  Only a batch where NO slot can absorb 2+ tokens
    falls back to the plain programs entirely."""
    from paddle_tpu.observability import metrics as _metrics
    p1, p2, _ = prompts()
    _, base = _greedy_streams(model, (p1, p2), (3, 14))
    _metrics.reset()
    eng, out = _greedy_streams(model, (p1, p2), (3, 14),
                               draft_model=draft_same, spec_decode=True,
                               spec_k=4)
    assert out == base
    st = eng.stats()["speculative"]
    # the mixed batch really ran spec ticks (4-budget-tail no longer
    # demotes) and the short slot was counted capped at least once
    assert st["ticks"] > 0
    assert st["ineligible_slots"] > 0
    snap = _metrics.snapshot()
    assert snap["serving.spec_ineligible_slots"]["series"][0]["value"] \
        == st["ineligible_slots"]
    # a batch with NOTHING to speculate (remaining budget 1 after the
    # prefill token) still uses the plain path
    _, base1 = _greedy_streams(model, (p1,), (2,))
    eng1, out1 = _greedy_streams(model, (p1,), (2,),
                                 draft_model=draft_same,
                                 spec_decode=True, spec_k=4)
    assert out1 == base1
    assert eng1.stats()["speculative"]["ticks"] == 0


# ------------------------------------------------- ISSUE 13: hostdraft

def test_ngram_drafter_proposals():
    """Host-side proposal table unit pins: periodic continuation,
    longest-match preference, incremental absorb, the self-match guard,
    and the head-repeat fallback."""
    from paddle_tpu.inference.drafting import NGramDraft
    d = NGramDraft()
    toks = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    # longest suffix [4,1,2] recurs at position 3 -> continuation wraps
    # the period exactly
    assert d.propose(toks, 6) == [3, 4, 1, 2, 3, 4]
    # incremental: absorb only the appended tokens, propose again
    assert d.propose(toks + [3, 4], 4) == [1, 2, 3, 4]
    assert d.matched == 2 and d.fallbacks == 0
    # no recurring suffix at all: head-repeat fallback
    d2 = NGramDraft()
    assert d2.propose([5, 6, 7], 3) == [7, 7, 7]
    assert d2.fallbacks == 1
    # order-1 match: continuation after the PRIOR occurrence, never the
    # suffix matching itself
    assert d2.propose([5, 6, 7, 5], 3) == [6, 7, 5]
    # longest order wins over a shorter, more recent match
    d3 = NGramDraft()
    assert d3.propose([9, 1, 2, 3, 8, 1, 2, 9, 1, 2], 2) == [3, 8]
    with pytest.raises(ValueError, match="min_n"):
        NGramDraft(max_n=2, min_n=3)
    # the engine's entry point: propose_stream appends only the new
    # output tail to an owned history (no per-tick concatenation) and
    # proposes identically to the list form
    d4, d5 = NGramDraft(), NGramDraft()
    prompt, out = [1, 2, 3, 4, 1, 2], []
    for tok in (3, 4, 1, 2, 3, 4):
        out.append(tok)
        assert d4.propose_stream(prompt, out, 4) \
            == d5.propose(prompt + out, 4)
    assert d4._toks == prompt + out     # absorbed incrementally


@pytest.mark.slow   # two engine builds (~7s); full runs cover it
def test_hostdraft_greedy_bit_identical(model):
    """THE tentpole headline: model-free n-gram drafting — no draft
    model, no draft pools — still emits greedy streams bit-identical
    to the plain engine, while the spec surface reports the ngram
    draft kind end-to-end (stats, flight records, lifecycle traces)."""
    from paddle_tpu.observability import flight_recorder as _flight
    from paddle_tpu.observability import metrics as _metrics
    p1, p2, p3 = prompts()
    _, base = _greedy_streams(model, (p1, p2, p3), (10, 8, 12))
    _metrics.reset()
    _flight.default_recorder().clear()
    eng, out = _greedy_streams(model, (p1, p2, p3), (10, 8, 12),
                               spec_decode=True, spec_draft="ngram",
                               spec_k=4)
    assert out == base
    st = eng.stats()["speculative"]
    assert st["draft"] == "ngram" and st["ticks"] > 0
    assert st["proposed_tokens"] > 0
    assert eng.dpools is None and eng.draft is None
    # per-slot accept rates are reported for the final occupants' runs
    assert all(0.0 <= v <= 1.0
               for v in st["per_slot_accept_rate"].values())
    recs = [r for r in _flight.default_recorder().snapshot()["steps"]
            if r.get("spec")]
    assert recs and all(r["spec_kind"] == "ngram" for r in recs)
    done = [r for r in eng.finished if r.trace is not None]
    assert done and all(r.trace["spec_draft"] == "ngram" for r in done)
    assert eng.stats()["free_blocks"] == eng.num_blocks
    assert eng.stats()["reserved"] == 0


def test_hostdraft_rejection_correction_is_lossless():
    """The deterministic-proposal correction: with ``q = one_hot(d)``
    the accept test is ``u <= p(d)`` and the residual is ``p`` minus
    ``d``'s mass — emitted tokens must still be EXACTLY p-distributed
    no matter how the proposals were chosen (here: adversarially, from
    a fixed wrong-ish token)."""
    import jax.numpy as jnp
    from paddle_tpu.inference.speculative import accept_and_choose
    from paddle_tpu.models.generation import _process_logits
    rng = np.random.RandomState(5)
    V, k, N = 24, 2, 4000
    t_logits = (rng.randn(V) * 2).astype(np.float32)
    temp, top_k, top_p = 0.8, 12, 0.9
    filtered = np.asarray(_process_logits(
        jnp.asarray(t_logits)[None], temp, top_k, top_p))[0]
    probs = np.exp(filtered - filtered.max())
    probs = probs / probs.sum()
    # deterministic proposals: half the slots propose the target's
    # argmax (plausible n-gram hit), half a low-probability token
    best = int(np.argmax(probs))
    worst = int(np.argsort(probs)[len(probs) // 2])
    dtoks = np.where((np.arange(N) % 2)[:, None] == 0, best,
                     worst).astype(np.int32)
    dtoks = np.broadcast_to(dtoks, (N, k)).copy()
    dprobs = np.zeros((N, k, V), np.float32)
    np.put_along_axis(dprobs, dtoks[..., None], 1.0, axis=-1)
    tlog = jnp.asarray(np.tile(t_logits, (N, k + 1, 1)))
    chosen, m, a, _ = accept_and_choose(
        tlog, jnp.asarray(dtoks), jnp.asarray(dprobs),
        jnp.ones((N,), bool), jnp.full((N,), temp, jnp.float32),
        jnp.full((N,), top_k, jnp.int32), jnp.full((N,), top_p,
                                                   jnp.float32),
        jnp.arange(N, dtype=jnp.uint32), jnp.full((N,), 16, jnp.int32))
    first = np.asarray(chosen)[:, 0]
    counts = np.bincount(first, minlength=V) / N
    assert counts[probs == 0].sum() == 0
    np.testing.assert_allclose(counts, probs, atol=0.05)


def test_finish_kcap_pins_per_slot_emit_rule():
    """Unit pin of the per-slot emit cap: ``m = min(1 + min(a, k-1),
    kcap)`` and ``new_last`` tracks the capped emission."""
    import jax.numpy as jnp
    from paddle_tpu.inference.speculative import _finish
    B, k, V = 3, 3, 8
    # all three rows fully accept the draft chain 5, 6, 7
    tl = np.full((B, k, V), -10.0, np.float32)
    tl[:, 0, 5] = tl[:, 1, 6] = tl[:, 2, 7] = 0.0
    dtoks = np.tile(np.array([5, 6, 7], np.int32), (B, 1))
    toks, counts, accepts, new_lens, new_last = _finish(
        None, jnp.asarray(tl), jnp.asarray(dtoks),
        jnp.zeros((B, k, V), jnp.float32), jnp.zeros((B,), bool),
        jnp.ones((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
        jnp.ones((B,), jnp.float32), jnp.zeros((B,), jnp.uint32),
        jnp.asarray([4, 4, 0], jnp.int32),       # row 2 inactive
        jnp.asarray([3, 2, 3], jnp.int32))       # row 1 capped at 2
    assert list(np.asarray(counts)) == [3, 2, 0]
    assert list(np.asarray(accepts)) == [3, 3, 0]   # raw accepts uncapped
    assert list(np.asarray(new_lens)) == [7, 6, 0]
    assert int(new_last[0]) == 7 and int(new_last[1]) == 6
    assert int(new_last[2]) == 0                    # inactive masked


@pytest.mark.slow   # compile-heavy composition pin; full runs cover it
def test_hostdraft_sampled_reproducible_and_overlap_invariant(model):
    """Sampled hostdraft streams are a pure function of the request
    seed (the accept/residual PRNG streams are position-keyed,
    proposals are deterministic), and invariant to the overlap flag —
    ngram ticks never chain, but plain<->spec boundaries shift."""
    p1, p2, _ = prompts()

    def serve():
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, spec_decode=True,
                            spec_draft="ngram", spec_k=3)
        g = eng.add_request(Request(p1, max_new_tokens=10))
        s = eng.add_request(Request(p2, max_new_tokens=10,
                                    do_sample=True, temperature=0.9,
                                    top_k=40, seed=7))
        eng.run()
        return eng, [list(g.output_ids), list(s.output_ids)]

    with flag_guard(serving_overlap=True):
        eng, first = serve()
        assert eng.stats()["speculative"]["ticks"] > 0
        _, again = serve()
    assert again == first
    with flag_guard(serving_overlap=False):
        _, sync = serve()
    assert sync == first


@pytest.mark.slow   # compiles every ladder rung; full runs cover it
def test_adaptive_k_transitions_stay_lossless(model):
    """Adaptive k on a repetitive workload: the controller really
    steps k across the ladder (up on high acceptance) and the greedy
    stream remains bit-identical to the plain engine ACROSS the
    transitions.  On a hostile (random) workload it steps back down."""
    rng = np.random.RandomState(3)
    pat = list(rng.randint(1, 1000, (4,)))
    rep = np.array(pat * 12)

    def serve(**kw):
        eng = ServingEngine(model, max_batch=2, max_context=256,
                            block_size=16, **kw)
        r = eng.add_request(Request(rep, max_new_tokens=40))
        eng.run()
        return eng, list(r.output_ids)

    _, base = serve()
    eng, out = serve(spec_decode=True, spec_draft="ngram",
                     spec_adaptive=True, spec_k_ladder="2,4,8")
    assert out == base
    st = eng.stats()["speculative"]
    assert st["adaptive"] and st["ladder"] == [2, 4, 8]
    assert st["k_switches"] >= 1 and st["k_now"] > 2
    assert st["accept_rate"] > 0.5


@pytest.mark.slow   # compiles two ladder rungs of the model-draft
                    # spec program; full runs cover it
def test_adaptive_k_steps_for_model_draft_under_overlap(model,
                                                        draft_same):
    """Review regression: model-draft spec ticks CHAIN under the
    default overlap flag and a chained dispatch reuses its
    predecessor's k — so the overlap gate must force a boundary while
    a k step is due, or the adaptive controller would be inert exactly
    when the full-accept draft should ramp it up."""
    p1, p2, _ = prompts()
    with flag_guard(serving_overlap=True):
        eng, out = _greedy_streams(model, (p1, p2), (20, 20),
                                   draft_model=draft_same,
                                   spec_decode=True, spec_adaptive=True,
                                   spec_k_ladder="2,4")
        _, base = _greedy_streams(model, (p1, p2), (20, 20))
    assert out == base
    st = eng.stats()["speculative"]
    assert st["accept_rate"] == 1.0
    assert st["k_switches"] >= 1 and st["k_now"] == 4


@pytest.mark.slow   # compile-heavy composition pin; full runs cover it
def test_hostdraft_tp2_greedy_bit_parity(model):
    """Composition: ngram drafting x tp_degree=2 — proposals replicated
    (rank-0 broadcast), verify sharded — greedy streams bit-identical
    to the plain degree-1 engine."""
    p1, p2, _ = prompts()
    _, base = _greedy_streams(model, (p1, p2), (8, 8))
    eng, out = _greedy_streams(model, (p1, p2), (8, 8), tp_degree=2,
                               spec_decode=True, spec_draft="ngram",
                               spec_k=3)
    assert out == base
    assert eng.stats()["speculative"]["ticks"] > 0
    assert eng.stats()["tp_degree"] == 2


def test_spec_draft_and_ladder_validation(model, draft_same):
    """ngram is model-free (a draft_model is a usage error), draft
    kinds are validated, and adaptive ladders reject rungs < 2."""
    with pytest.raises(ValueError, match="model-free"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      draft_model=draft_same, spec_decode=True,
                      spec_draft="ngram")
    with pytest.raises(ValueError, match="spec_draft"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      spec_decode=True, spec_draft="suffix")
    with pytest.raises(ValueError, match="ladder"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      spec_decode=True, spec_draft="ngram",
                      spec_adaptive=True, spec_k_ladder="1,4")


def test_spec_constructor_validation(model, draft_same):
    with pytest.raises(ValueError, match="draft model"):
        ServingEngine(model, max_batch=2, max_context=64,
                      block_size=16, spec_decode=True)
    paddle.seed(1)
    bad_vocab = GPTForCausalLM(GPTConfig(
        vocab_size=512, hidden_size=64, num_layers=1, num_heads=2,
        max_seq_len=256))
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      draft_model=bad_vocab, spec_decode=True)
    with pytest.raises(ValueError, match="spec_k"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      draft_model=draft_same, spec_decode=True,
                      spec_k=0)
    paddle.seed(1)
    short = GPTForCausalLM(GPTConfig(
        vocab_size=1024, hidden_size=64, num_layers=1, num_heads=2,
        max_seq_len=32))
    with pytest.raises(ValueError, match="max_seq_len"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      draft_model=short, spec_decode=True)
