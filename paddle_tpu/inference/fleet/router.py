"""Prefix-affinity fleet router (ISSUE 16 tentpole a).

One stdlib HTTP daemon in front of N engine replicas:

* ``POST /generate`` — routed by **prefix-hash affinity**: the blake2b
  chain hash of the prompt's first ``FLAGS_fleet_affinity_tokens``
  tokens (:func:`affinity_key` — the SAME hash the engines' prefix
  caches chain, so when it matches the engine block size the key IS the
  first-block hash), rendezvous-hashed over the replicas.  Shared-prefix
  traffic therefore lands on the replica whose KV pool already holds
  that prefix; when a replica drains or dies the rendezvous order
  reroutes ONLY its share, and routes it back after restart.  The
  response is a byte-faithful SSE passthrough — the router never parses
  the token stream, it pumps bytes and propagates disconnects both ways.
* shedding by **predicted TTFT**: each replica's ``/healthz`` carries
  queue depth + ``ttft_evidence`` (admission rate, recent median TTFT —
  serving.py keeps these always-on).  :func:`predict_ttft_s` turns that
  into the TTFT a request would see if routed there NOW (queue-position
  model: position/admission-rate + base).  With
  ``FLAGS_fleet_ttft_budget_ms`` set, a request every ready replica
  predicts over budget is answered 429 at the router — before any
  engine queues it into a certain SLO violation.  This replaces the
  observed-breach shedding of PR 11 at the fleet layer: by the time a
  p99 sketch shows the breach, the queue that caused it is already
  serving violations.
* failover: a connect/dispatch failure on the chosen replica (chaos
  site ``fleet.proxy.connect``) marks it down and retries the next
  candidate in rendezvous order — the zero-dropped-requests mechanic
  the rolling-restart drill (replica.py) leans on.
* ``GET /healthz`` (router's own: ready iff any replica is) and
  ``GET /fleet`` (routing table: per-replica readiness, queue depth,
  predicted TTFT, cordon state, route counts).

The router holds no device state and no tokens — it is restartable at
any moment and horizontally dumb on purpose; all KV locality lives in
the affinity function.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from ... import flags as _flags
from ...observability import federation as _federation
from ...observability import flight_recorder as _flight
from ...observability import metrics as _metrics
from ...observability import tracing as _tracing
from ...testing import chaos as _chaos
from ..prefix_cache import _chain

__all__ = ["FleetRouter", "affinity_key", "predict_ttft_s",
           "rendezvous_order"]

_M_ROUTED = _metrics.counter(
    "fleet.router.requests", "requests proxied to a replica, by "
    "replica=<name>")
_M_AFFINITY = _metrics.counter(
    "fleet.router.affinity", "affinity routing outcomes: outcome=hit "
    "(request landed on its rendezvous home replica) or outcome="
    "fallback (home not ready / over budget — rerouted)")
_M_SHEDS = _metrics.counter(
    "fleet.router.sheds", "requests shed 429 at the router because "
    "every ready replica's PREDICTED TTFT (queue-position model) "
    "exceeded FLAGS_fleet_ttft_budget_ms")
_M_FAILOVERS = _metrics.counter(
    "fleet.router.failovers", "proxy attempts that failed over to the "
    "next replica in rendezvous order (connect failure or 503)")
_M_UNROUTABLE = _metrics.counter(
    "fleet.router.unroutable", "requests answered 503: no ready "
    "replica accepted the proxy attempt")
_M_REPLAYED = _metrics.counter(
    "fleet.replayed_requests", "accepted streams that died BEFORE the "
    "first token frame reached the client and were replayed on another "
    "replica (nothing was delivered, so the replay is idempotent)")
_M_SLO_BURN = _metrics.gauge(
    "fleet.slo_burn", "per-replica SLO error-budget burn rate over the "
    "FAST window (fleet_burn_fast_window_s), by replica=<name>: bad-"
    "event fraction (TTFT-SLO violations + error/poisoned outcomes) "
    "divided by fleet_error_budget — 1.0 spends the budget exactly at "
    "the sustainable rate")
_M_FED_POLLS = _metrics.counter(
    "fleet.federation.polls", "metrics-federation snapshot polls, by "
    "outcome=ok|error")


def affinity_key(prompt_ids: Sequence[int],
                 affinity_tokens: Optional[int] = None) -> bytes:
    """The prompt's routing key: blake2b chain hash (prefix_cache's
    ``_chain``, empty parent) of its first ``affinity_tokens`` tokens —
    prompts sharing that prefix share the key, and when
    ``affinity_tokens`` equals the engine block size the key is
    bit-identical to the prefix cache's first-block hash."""
    if affinity_tokens is None:
        affinity_tokens = int(_flags.get_flag("fleet_affinity_tokens"))
    return _chain(b"", list(prompt_ids[:max(int(affinity_tokens), 1)]))


def rendezvous_order(key: bytes, names: Sequence[str]) -> List[str]:
    """Highest-random-weight order of ``names`` for ``key``: stable
    under membership change (a leaving replica reroutes ONLY its own
    keys; everyone else's affinity survives), no ring state."""
    def weight(name: str) -> Tuple[bytes, str]:
        h = hashlib.blake2b(key, digest_size=8)
        h.update(name.encode())
        return (h.digest(), name)
    return sorted(names, key=weight, reverse=True)


def predict_ttft_s(doc: dict) -> float:
    """Queue-position TTFT model over one replica's /healthz document:
    the TTFT a request routed there NOW should see.

    ``position`` requests must admit first (everything waiting, plus
    one slot-holder finishing when no slot is free); each costs
    ``1/admit_rate`` seconds of queue wait at the replica's recent
    admission rate, then the request itself pays the recent median
    TTFT.  With no rate evidence each queued request is costed at one
    base TTFT.  A cold replica (no evidence at all) predicts ~0 — the
    shed gate never starves an idle fleet.

    The observed admission rate alone is a trap under a load swing: it
    reflects the RECENT past, not what the decode loop can drain.  When
    the replica ships live TPOT evidence (``tpot_p50_s`` +
    ``avg_tokens_out``, ISSUE 17) the rate is capped by the decode
    capacity ``slots / (avg_tokens_out * tpot)`` — slots turn over one
    request per ``avg_tokens_out * tpot`` seconds, so a stale-high
    admission rate can no longer hide a deep queue behind an
    optimistic drain projection.  Without TPOT evidence the model is
    bit-identical to the PR 16 behavior."""
    ev = doc.get("ttft_evidence") or {}
    base = float(ev.get("ttft_p50_s") or 0.0)
    rate = float(ev.get("admit_rate_per_s") or 0.0)
    tpot = float(ev.get("tpot_p50_s") or 0.0)
    avg_out = float(ev.get("avg_tokens_out") or 0.0)
    slots = int(doc.get("slots", 0) or 0)
    if tpot > 0 and avg_out > 0 and slots > 0:
        capacity = slots / (avg_out * tpot)
        rate = min(rate, capacity) if rate > 0 else capacity
    position = int(doc.get("waiting", 0) or 0)
    if int(doc.get("free_slots", 1) or 0) <= 0:
        position += 1
    queue_wait = position / rate if rate > 0 else position * base
    return base + queue_wait


class _ReplicaState:
    """The router's last-polled view of one replica."""

    __slots__ = ("name", "host", "port", "doc", "ready", "cordoned",
                 "last_poll", "last_err", "routed", "snapshot", "clock",
                 "auto_cordoned", "burn_fast", "burn_slow")

    def __init__(self, name: str, addr: str):
        host, _, port = addr.rpartition(":")
        self.name = name
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.doc: dict = {}
        self.ready = False
        self.cordoned = False
        self.last_poll = 0.0
        self.last_err: Optional[str] = None
        self.routed = 0
        # fleet telescope state (ISSUE 17): last federation snapshot,
        # the clock-offset estimate from /healthz round-trips, and the
        # burn monitor's readout / auto-cordon marker
        self.snapshot: Optional[dict] = None
        self.clock = _tracing.ClockSync()
        self.auto_cordoned = False
        self.burn_fast: Optional[float] = None
        self.burn_slow: Optional[float] = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def view(self) -> dict:
        out = {"addr": self.addr, "ready": self.ready,
               "cordoned": self.cordoned, "routed": self.routed,
               "queue_depth": int(self.doc.get("queue_depth", 0) or 0),
               "predicted_ttft_ms": round(
                   predict_ttft_s(self.doc) * 1e3, 3),
               "last_err": self.last_err}
        if self.auto_cordoned:
            out["auto_cordoned"] = True
        if self.burn_fast is not None or self.burn_slow is not None:
            out["slo_burn"] = {"fast": self.burn_fast,
                               "slow": self.burn_slow}
        if self.clock.offset_s is not None:
            out["clock"] = self.clock.view()
        return out


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_fleet/1.0"
    # self.server.router is the owning FleetRouter

    def log_message(self, format, *args):  # noqa: A002 - http.server API
        pass

    def _send(self, code: int, body: dict) -> None:
        raw = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        try:
            router = self.server.router
            if self.path.startswith("/healthz"):
                doc = router.healthz()
                self._send(200 if doc["ready"] else 503, doc)
            elif self.path.startswith("/fleet/metrics"):
                # before the /fleet prefix match: the federated fleet_*
                # view in Prometheus text exposition (ISSUE 17)
                raw = router.fleet_metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
            elif self.path.startswith("/fleet"):
                self._send(200, router.describe())
            else:
                self._send(404, {"error": "endpoints: /healthz /fleet "
                                          "/fleet/metrics"})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        try:
            if self.path.startswith("/generate"):
                self.server.router._route_generate(self)
            else:
                self._send(404, {"error": "POST endpoints: /generate"})
        except (BrokenPipeError, ConnectionResetError):
            pass


class FleetRouter:
    """The fleet front door.  ``replicas`` maps name -> ``host:port``
    of an engine replica frontend (observability/http.py surface);
    ``port=0`` binds an ephemeral loopback port (tests).  A background
    poller refreshes every replica's /healthz at
    ``FLAGS_fleet_poll_interval_s``; routing reads the cached view and
    proxy failures update it inline (a dead replica is routed around
    immediately, not at the next poll tick)."""

    def __init__(self, replicas: Dict[str, str], port: Optional[int] = None,
                 affinity_tokens: Optional[int] = None,
                 ttft_budget_ms: Optional[float] = None,
                 poll_interval_s: Optional[float] = None,
                 proxy_timeout_s: float = 30.0,
                 retry_window_s: float = 5.0,
                 metrics_interval_s: Optional[float] = None,
                 flight_recorder: Optional[
                     "_flight.FlightRecorder"] = None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.affinity_tokens = int(
            affinity_tokens if affinity_tokens is not None
            else _flags.get_flag("fleet_affinity_tokens"))
        self.ttft_budget_ms = float(
            ttft_budget_ms if ttft_budget_ms is not None
            else _flags.get_flag("fleet_ttft_budget_ms"))
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else _flags.get_flag("fleet_poll_interval_s"))
        self.metrics_interval_s = float(
            metrics_interval_s if metrics_interval_s is not None
            else _flags.get_flag("fleet_metrics_interval_s"))
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.retry_window_s = float(retry_window_s)
        self._lock = threading.Lock()
        self._states = {name: _ReplicaState(name, addr)
                        for name, addr in replicas.items()}
        # host-side route accounting (always on, unlike the metrics
        # registry): the acceptance affinity-hit-rate gate reads these
        self.routed = 0
        self.affinity_hits = 0
        self.fallbacks = 0
        self.sheds = 0
        self.failovers = 0
        self.unroutable = 0
        self.replayed = 0
        # fleet telescope (ISSUE 17): per-router flight recorder (an
        # in-process fleet must not interleave router spans into the
        # replicas' rings), the federated registry, the burn monitor
        self._flight = flight_recorder
        self._fed_lock = threading.Lock()
        self._fed_registry: Optional[_metrics.Registry] = None
        self._fed_time = 0.0
        self._last_metrics_poll = 0.0
        self._burn = _federation.BurnRateMonitor(
            fast_window_s=float(
                _flags.get_flag("fleet_burn_fast_window_s")),
            slow_window_s=float(
                _flags.get_flag("fleet_burn_slow_window_s")),
            threshold=float(_flags.get_flag("fleet_burn_threshold")),
            error_budget=float(_flags.get_flag("fleet_error_budget")))
        self._flightrec().record_event("replica_meta", replica="router")
        self._closed = threading.Event()
        self.poll_all()
        if port is None:
            port = int(_flags.get_flag("fleet_router_port"))
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = self
        self.port = int(self._httpd.server_address[1])
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router",
            daemon=True)
        self._serve_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-router-poll", daemon=True)
        self._poll_thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._closed.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._serve_thread.join(timeout=5)
        self._poll_thread.join(timeout=5)

    def _flightrec(self) -> "_flight.FlightRecorder":
        rec = self._flight
        return rec if rec is not None else _flight.default_recorder()

    # ------------------------------------------------------- health view
    def _poll_loop(self) -> None:
        while not self._closed.wait(self.poll_interval_s):
            self.poll_all()
            if self.metrics_interval_s > 0 and (
                    time.monotonic() - self._last_metrics_poll
                    >= self.metrics_interval_s):
                self.poll_metrics_all()

    def poll_all(self) -> None:
        for name in list(self._states):
            self.poll_once(name)

    def poll_once(self, name: str) -> dict:
        """Refresh one replica's /healthz view.  A refused/failed probe
        marks the replica not-ready (routed around) — never raises.
        The round-trip doubles as a clock-offset sample: the reply's
        ``unix_time`` against the local send/receive times updates the
        replica's min-RTT :class:`..observability.tracing.ClockSync`
        estimate (error bound rtt/2) the fleet-trace merge aligns
        timelines with."""
        st = self._states[name]
        doc: dict = {}
        err: Optional[str] = None
        t0 = time.time()
        try:
            conn = http.client.HTTPConnection(st.host, st.port,
                                              timeout=2.0)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                doc = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError) as e:
            err = f"{type(e).__name__}: {e}"[:120]
        t1 = time.time()
        improved = False
        if err is None and doc.get("unix_time"):
            try:
                improved = st.clock.update(
                    t0, float(doc["unix_time"]), t1)
            except (TypeError, ValueError):
                pass
        with self._lock:
            st.doc = doc
            st.ready = bool(doc.get("ready"))
            st.last_err = err
            st.last_poll = time.monotonic()
        if improved:
            self._flightrec().record_event(
                "clock_sync", replica=name, **st.clock.view())
        return doc

    # ------------------------------------- metrics federation (ISSUE 17)
    def poll_metrics_once(self, name: str) -> Optional[dict]:
        """Fetch one replica's /metrics/snapshot (mergeable registry
        state + engine telemetry).  Never raises; a failed poll keeps
        the previous snapshot (stale beats absent for the merge)."""
        st = self._states[name]
        try:
            conn = http.client.HTTPConnection(st.host, st.port,
                                              timeout=2.0)
            try:
                conn.request("GET", "/metrics/snapshot")
                resp = conn.getresponse()
                if resp.status != 200:
                    raise ValueError(f"status {resp.status}")
                doc = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError):
            _M_FED_POLLS.inc(outcome="error")
            return None
        _M_FED_POLLS.inc(outcome="ok")
        with self._lock:
            st.snapshot = doc
        return doc

    def poll_metrics_all(self) -> None:
        """One federation sweep: refresh every replica's snapshot,
        rebuild the merged fleet registry, feed the burn monitor and
        apply the auto-cordon policy."""
        self._last_metrics_poll = time.monotonic()
        for name in list(self._states):
            self.poll_metrics_once(name)
        with self._lock:
            snaps = {n: s.snapshot for n, s in self._states.items()
                     if s.snapshot is not None}
        merged = _federation.merge_snapshots(snaps)
        with self._fed_lock:
            self._fed_registry = merged
            self._fed_time = time.monotonic()
        self._update_burn(snaps)

    def _update_burn(self, snaps: Dict[str, dict]) -> None:
        """Feed the burn monitor from each snapshot's engine telemetry
        (good = finished requests, bad = TTFT-SLO violations + error/
        poisoned outcomes) and apply the cordon policy."""
        for name, snap in snaps.items():
            eng = (snap or {}).get("engine") or {}
            outcomes = eng.get("outcomes") or {}
            bad = (float(outcomes.get("error", 0))
                   + float(outcomes.get("poisoned", 0))
                   + float(eng.get("slo_violations_ttft", 0)))
            good = float(eng.get("finished", 0))
            self._burn.observe(name, good=good, bad=bad)
            st = self._states[name]
            st.burn_fast = self._burn.burn(name, self._burn.fast_window_s)
            st.burn_slow = self._burn.burn(name, self._burn.slow_window_s)
            if st.burn_fast is not None:
                _M_SLO_BURN.set(round(st.burn_fast, 4), replica=name)
        if not bool(_flags.get_flag("fleet_slo_burn_cordon")):
            return
        for name in list(snaps):
            st = self._states[name]
            if not st.cordoned and self._burn.burning(name):
                with self._lock:
                    # never cordon the LAST uncordoned replica: the
                    # cordon is a preference, and an all-cordoned fleet
                    # only survives via the degraded plan — prefer
                    # keeping one normal candidate
                    others = [s for s in self._states.values()
                              if s is not st and not s.cordoned]
                    if not others:
                        continue
                    st.cordoned = True
                    st.auto_cordoned = True
                self._flightrec().record_event(
                    "slo_cordon", replica=name,
                    fast_burn=st.burn_fast, slow_burn=st.burn_slow)
            elif st.auto_cordoned and self._burn.recovered(name):
                with self._lock:
                    st.cordoned = False
                    st.auto_cordoned = False
                self._flightrec().record_event(
                    "slo_uncordon", replica=name,
                    fast_burn=st.burn_fast)

    def fleet_metrics_text(self) -> str:
        """The federated fleet_* view as Prometheus text.  With the
        federation poller off (fleet_metrics_interval_s == 0) this
        federates once on demand — a scrape always answers."""
        with self._fed_lock:
            reg = self._fed_registry
        if reg is None:
            self.poll_metrics_all()
            with self._fed_lock:
                reg = self._fed_registry
        if reg is None:
            return ""
        return _federation.render_fleet(reg)

    def cordon(self, name: str) -> None:
        """Stop routing NEW requests to ``name`` (rolling restart takes
        the replica out BEFORE draining it — no window where the router
        races the healthz flip).  A manual cordon clears the
        auto-cordon marker: the burn monitor no longer owns (and will
        not auto-lift) this cordon."""
        with self._lock:
            self._states[name].cordoned = True
            self._states[name].auto_cordoned = False

    def uncordon(self, name: str) -> None:
        with self._lock:
            self._states[name].cordoned = False
            self._states[name].auto_cordoned = False

    def healthz(self) -> dict:
        with self._lock:
            views = {n: s.view() for n, s in self._states.items()}
        return {"ok": True, "router": True,
                "ready": any(v["ready"] and not v["cordoned"]
                             for v in views.values()),
                "replicas": views}

    def describe(self) -> dict:
        doc = self.healthz()
        doc["stats"] = self.stats()
        # fleet-aggregate latency view from the federated sketches
        # (present once a federation sweep has run) + the burn readout
        with self._fed_lock:
            reg = self._fed_registry
        if reg is not None:
            doc["fleet_latency"] = _federation.fleet_latency(reg)
        burn = self._burn.view()
        if burn:
            doc["slo_burn"] = burn
        return doc

    def stats(self) -> dict:
        with self._lock:
            per = {n: s.routed for n, s in self._states.items()}
        return {"routed": self.routed, "affinity_hits": self.affinity_hits,
                "fallbacks": self.fallbacks, "sheds": self.sheds,
                "failovers": self.failovers, "unroutable": self.unroutable,
                "replayed": self.replayed,
                "affinity_hit_rate": round(
                    self.affinity_hits / self.routed, 4)
                if self.routed else None,
                "per_replica": per}

    # ---------------------------------------------------------- routing
    def plan(self, prompt_ids: Sequence[int]) -> dict:
        """The routing decision, sans proxying (unit-testable): the
        rendezvous home, the try-order over ready+uncordoned replicas
        (budget-violating candidates dropped when a budget is set), and
        the per-candidate predicted TTFT.

        The health view is a PREFERENCE, not a verdict: when it says
        nobody is ready (a poll can time out under load and mark a
        perfectly alive replica down), the plan degrades to every
        uncordoned replica in rendezvous order and lets the proxy
        attempt decide — answering 503 off a stale view would drop
        requests a replica could serve.  Predictions (and therefore the
        shed gate) only apply to the ready view; a degraded plan never
        sheds."""
        key = affinity_key(prompt_ids, self.affinity_tokens)
        with self._lock:
            home_order = rendezvous_order(key, list(self._states))
            ready = [n for n in home_order
                     if self._states[n].ready
                     and not self._states[n].cordoned]
            uncordoned = [n for n in home_order
                          if not self._states[n].cordoned]
            predicted = {n: predict_ttft_s(self._states[n].doc)
                         for n in ready}
        home = home_order[0]
        order = ready
        shed = False
        degraded = False
        if self.ttft_budget_ms > 0 and ready:
            budget_s = self.ttft_budget_ms / 1e3
            order = [n for n in ready if predicted[n] <= budget_s]
            shed = not order
        if not order and not shed and uncordoned:
            order = uncordoned
            degraded = True
        return {"key": key.hex(), "home": home, "order": order,
                "ready": ready, "shed": shed, "degraded": degraded,
                "predicted_ttft_ms": {
                    n: round(p * 1e3, 3) for n, p in predicted.items()}}

    def _route_generate(self, handler: _RouterHandler) -> None:
        try:
            n = int(handler.headers.get("Content-Length") or 0)
            body = handler.rfile.read(n)
            prompt_ids = [int(t)
                          for t in json.loads(body or b"{}")["prompt_ids"]]
        except (KeyError, TypeError, ValueError) as e:
            handler._send(400, {"error": f"bad request body: {e!r}"})
            return
        # distributed trace (ISSUE 17): adopt the client's trace id or
        # mint one, then forward `<trace_id>-<router_span>` so the
        # replica's Request joins the same trace with the router hop as
        # its parent span.  Flag off: forward a client header verbatim
        # (explicit context still propagates), mint nothing.
        client_header = handler.headers.get(_tracing.TRACE_HEADER)
        trace_id, _ = _tracing.parse_header(client_header)
        trace_header = client_header if trace_id else None
        router_span = None
        if bool(_flags.get_flag("fleet_trace")):
            if trace_id is None:
                trace_id = _tracing.mint_trace_id()
            router_span = _tracing.new_span_id()
            trace_header = _tracing.format_header(trace_id, router_span)
        t_route0 = time.time()
        plan = self.plan(prompt_ids)
        if router_span is not None:
            self._flightrec().record_span(
                "plan", "router", t_route0, time.time(),
                trace_id=trace_id, span=router_span, home=plan["home"],
                degraded=plan["degraded"])
        if plan["shed"]:
            self.sheds += 1
            _M_SHEDS.inc()
            handler._send(429, {
                "error": "shed", "reason": "predicted_ttft",
                "budget_ms": self.ttft_budget_ms,
                "predicted_ttft_ms": plan["predicted_ttft_ms"]})
            return
        # A failed pass over the plan is retried (fresh poll, fresh
        # plan) within a bounded window before answering 503: mid-
        # rolling-restart every candidate can be TRANSIENTLY unusable
        # for a beat (one draining, the next chaos-marked down) and
        # giving up on that beat drops a request a replica would have
        # served a poll later.  Shed is never retried — over-budget is
        # a verdict, not a transient.
        deadline = time.monotonic() + self.retry_window_s
        first_pass = True
        while True:
            for i, name in enumerate(plan["order"]):
                st = self._states[name]
                if i or not first_pass:
                    self.failovers += 1
                    _M_FAILOVERS.inc()
                got = self._proxy_begin(st, body, trace_header)
                if got is None:
                    continue
                # account BEFORE relaying: the replica has accepted the
                # request, and a client that finishes reading the stream
                # must observe the updated stats (the relay can outrun a
                # post-relay increment)
                self.routed += 1
                st.routed += 1
                _M_ROUTED.inc(replica=name)
                if name == plan["home"]:
                    self.affinity_hits += 1
                    _M_AFFINITY.inc(outcome="hit")
                else:
                    self.fallbacks += 1
                    _M_AFFINITY.inc(outcome="fallback")
                t_proxy0 = time.time()
                outcome = self._relay(handler, *got)
                if router_span is not None:
                    self._flightrec().record_span(
                        "proxy", "router", t_proxy0, time.time(),
                        trace_id=trace_id, span=router_span,
                        replica=name, outcome=outcome)
                if outcome == "replay":
                    # the stream died (or opened with a terminal error
                    # frame) before the FIRST token frame left the
                    # router: the client saw nothing, so re-routing the
                    # request to the next candidate is idempotent —
                    # unlike a mid-stream death, which already
                    # delivered tokens and must surface as truncation
                    self.replayed += 1
                    _M_REPLAYED.inc()
                    continue
                return
            if time.monotonic() >= deadline:
                break
            first_pass = False
            time.sleep(min(0.05, self.poll_interval_s))
            self.poll_all()
            plan = self.plan(prompt_ids)
        self.unroutable += 1
        _M_UNROUTABLE.inc()
        handler._send(503, {"error": "no replica accepted the request",
                            "tried": plan["order"]})

    def _proxy_begin(self, st: _ReplicaState, body: bytes,
                     trace_header: Optional[str] = None):
        """One proxy attempt up to the response line: POST the original
        body to the replica, forwarding the trace context header so the
        replica's records join the router's trace.  Returns
        ``(conn, resp)`` once the replica has ACCEPTED the request (any
        status but 503 — a replica's own 400 is authoritative: the
        request reached an engine); None on a pre-response failure or a
        503 (draining/warming — candidate unusable, caller fails over),
        marking the replica down inline."""
        conn = None
        try:
            _chaos.inject("fleet.proxy.connect")
            conn = http.client.HTTPConnection(
                st.host, st.port, timeout=self.proxy_timeout_s)
            headers = {"Content-Type": "application/json"}
            if trace_header:
                headers[_tracing.TRACE_HEADER] = trace_header
            conn.request("POST", "/generate", body=body, headers=headers)
            resp = conn.getresponse()
        except OSError as e:
            if conn is not None:
                conn.close()
            with self._lock:
                st.ready = False
                st.last_err = f"{type(e).__name__}: {e}"[:120]
            return None
        if resp.status == 503:      # draining/warming: next candidate
            conn.close()
            with self._lock:
                st.ready = False
            return None
        return conn, resp

    def _relay(self, handler: _RouterHandler, conn, resp) -> str:
        """Pump the accepted response through byte-for-byte (SSE
        passthrough — chunks forwarded as they arrive, flushed
        immediately).

        Replay gate (ISSUE 20): for an SSE stream, NOTHING is written
        to the client until the first complete frame (``\\n\\n``
        boundary) arrives and classifies the stream.  A first frame
        that is a terminal ``event: error`` — or an upstream that dies
        before completing any frame — means zero bytes were delivered:
        the request is safely replayable on another replica and this
        returns ``"replay"`` without touching the client socket.  Once
        the first frame is a real token (or ``event: done``), headers +
        buffer flush and the relay degrades to the historical byte-
        faithful passthrough (``"delivered"`` even if the stream later
        truncates — the client already saw tokens, a replay would
        duplicate them).  Non-SSE responses (a replica's own 400 JSON
        is authoritative) relay immediately."""
        ctype = resp.headers.get("Content-Type", "")
        gate = resp.status == 200 and "text/event-stream" in ctype
        buf = b""
        if gate:
            try:
                while b"\n\n" not in buf:
                    chunk = resp.read1(65536)
                    if not chunk:       # upstream died pre-first-frame
                        conn.close()
                        return "replay"
                    buf += chunk
            except OSError:
                conn.close()
                return "replay"
            first = buf.split(b"\n\n", 1)[0]
            if first.startswith(b"event: error"):
                conn.close()
                return "replay"
        try:
            handler.send_response(resp.status)
            for h in ("Content-Type", "Cache-Control", "Content-Length"):
                v = resp.headers.get(h)
                if v is not None:
                    handler.send_header(h, v)
            handler.send_header("Connection", "close")
            handler.end_headers()
            if buf:
                handler.wfile.write(buf)
                handler.wfile.flush()
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                handler.wfile.write(chunk)
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass    # client hung up; closing upstream propagates cancel
        finally:
            conn.close()
        return "delivered"
