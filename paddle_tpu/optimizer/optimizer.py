"""Optimizer base + SGD/Momentum/Adam/AdamW/Adagrad/RMSProp/Adadelta/Adamax/Lamb.

Parity: `python/paddle/optimizer/optimizer.py` (+ adamw.py etc.).  TPU-native
detail: each optimizer's update rule is one jitted pure function applied
per-parameter (XLA fuses the elementwise chain; donated buffers update
in place in HBM — the analogue of the reference's fused multi-tensor
optimizer kernels).  Master weights (multi_precision) keep an fp32 shadow for
bf16/fp16 params like `optimizer.py` master-weight path.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase
from ..observability import metrics as _metrics
from .lr import LRScheduler

# per-leaf jitted-program dispatches ride the same instrument as the
# eager op dispatcher, so one metrics delta covers a whole train step
# (the fused path counts ONE optimizer.fused_step instead — fused.py)
_M_DISPATCH = _metrics.counter("dispatch.ops", "eager dispatches per op name")
_K_LEAF_UPDATE = (("op", "optimizer.leaf_update"),)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "RMSProp", "Adadelta", "Adamax", "Lamb"]

import weakref

# live-optimizer registry consumed by jit capture (paddle_tpu/jit/api.py):
# optimizer accumulators/step counters become captured-program state.
_optimizer_registry: "weakref.WeakSet" = weakref.WeakSet()


def _live_optimizers():
    return list(_optimizer_registry)


def _donation_safe() -> bool:
    """Donation must be off while the jit state-discovery pass records
    pre-step buffer references for rollback."""
    from ..ops import registry as _registry
    return _registry._trace_recorder is None


def _instance_update(opt, rule, value, grad, master, states, lr, wd, step):
    """Shared jitted-apply path for optimizers with per-instance rules."""
    donate = _donation_safe()
    cache = getattr(opt, "_rule_jits", None)
    if cache is None:
        cache = opt._rule_jits = {}
    jitted = cache.get(donate)
    if jitted is None:
        def apply(value, grad, master, states, lr, wd, step):
            work = master if master is not None else value
            grad = grad.astype(work.dtype)
            new_work, new_states = rule(work, grad, states, lr, wd, step)
            if master is not None:
                return new_work.astype(value.dtype), new_work, new_states
            return new_work, None, new_states
        jitted = cache[donate] = jax.jit(
            apply, static_argnames=("wd",),
            donate_argnums=(0, 2, 3) if donate else ())
    if _metrics._ENABLED:
        _M_DISPATCH.inc_key(_K_LEAF_UPDATE)
    return jitted(value, grad, master, states,
                  jnp.asarray(lr, jnp.float32), wd,
                  jnp.asarray(step, jnp.float32))


class Optimizer:
    _update_rule: Callable = None  # set by subclasses
    _state_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip: Optional[ClipGradBase] = None, name=None,
                 multi_precision: bool = False):
        if parameters is None:
            raise ValueError(
                "paddle_tpu is dygraph-first: pass `parameters=` explicitly")
        self._lr = learning_rate
        self._param_groups = self._build_groups(parameters)
        self._weight_decay = self._wd_value(weight_decay)
        self._l1 = self._l1_value(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[int, jax.Array]] = defaultdict(dict)
        self._global_step = 0
        self._aux_hooks: List[Callable] = []
        self._lr_override = None  # traced LR installed during jit capture
        _optimizer_registry.add(self)

    @staticmethod
    def _wd_value(weight_decay):
        """Returns the L2 coefficient; L1Decay is handled separately in
        _apply_one (sign-based grad term), never silently folded into L2."""
        if weight_decay is None:
            return 0.0
        from ..regularizer import L1Decay
        if isinstance(weight_decay, L1Decay):
            return 0.0
        if hasattr(weight_decay, "_coeff"):  # regularizer.L2Decay
            return float(weight_decay._coeff)
        return float(weight_decay)

    @staticmethod
    def _l1_value(weight_decay):
        from ..regularizer import L1Decay
        if isinstance(weight_decay, L1Decay):
            return float(weight_decay._coeff)
        return 0.0

    def _build_groups(self, parameters):
        parameters = list(parameters)
        if parameters and isinstance(parameters[0], dict):
            groups = []
            for g in parameters:
                g = dict(g)
                g["params"] = list(g["params"])
                groups.append(g)
            return groups
        return [{"params": parameters}]

    # ------------------------------------------------------------ lr
    def get_lr(self) -> float:
        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("set_lr not allowed with an LRScheduler")
        self._lr = float(value)

    def _create_master_weight(self, p: Parameter):
        key = id(p)
        mw = self._accumulators["master_weight"]
        if key not in mw:
            mw[key] = p._value.astype(jnp.float32)
        return mw[key]

    def _get_state(self, name: str, p: Parameter, like=None):
        key = id(p)
        store = self._accumulators[name]
        if key not in store:
            proto = like if like is not None else p._value
            store[key] = jnp.zeros(proto.shape, jnp.float32
                                   if self._multi_precision else proto.dtype)
        return store[key]

    def _set_state(self, name: str, p: Parameter, value):
        self._accumulators[name][id(p)] = value

    # ------------------------------------------------------------ step
    def _collect_work(self):
        """Collect across ALL groups first so ClipGradByGlobalNorm sees
        the true global norm (paddle clips the whole parameter list at
        once).  Returns (work, all_pg): work items are mutable
        [param, grad, lr, wd, l1] lists."""
        work = []
        all_pg = []
        for group in self._param_groups:
            lr = group.get("learning_rate", 1.0) * self.get_lr() \
                if "learning_rate" in group else self.get_lr()
            gwd = group.get("weight_decay", None)
            wd = self._wd_value(gwd) if "weight_decay" in group \
                else self._weight_decay
            l1 = self._l1_value(gwd) if "weight_decay" in group else self._l1
            for p in group["params"]:
                if p.grad is None or p.stop_gradient:
                    continue
                work.append([p, p.grad, lr, wd, l1])
                all_pg.append((p, p.grad))
        return work, all_pg

    @jax.named_scope("optimizer_step")
    def step(self):
        from . import fused as _fused
        self._global_step += 1
        work, all_pg = self._collect_work()
        if not _fused.try_step(self, work):
            self._apply_per_leaf(work, all_pg)
        for hook in self._aux_hooks:
            hook(self)

    def _apply_per_leaf(self, work, all_pg):
        """The legacy one-program-per-parameter path (FLAGS_fused_optimizer
        off, or an irregular step the fused plan declined)."""
        if self._grad_clip is not None:
            clipped = self._grad_clip(all_pg)
            for item, (_, g) in zip(work, clipped):
                item[1] = g
        for p, g, lr, wd, l1 in work:
            if g is None:
                continue
            self._apply_one(p, g._value if isinstance(g, Tensor) else g,
                            lr * p.optimize_attr.get("learning_rate", 1.0),
                            wd, l1)

    def _apply_one(self, p: Parameter, grad, lr: float, wd: float,
                   l1: float = 0.0):
        if l1:
            grad = grad + l1 * jnp.sign(p._value.astype(grad.dtype))
        use_master = self._multi_precision and p._value.dtype in (
            jnp.float16, jnp.bfloat16)
        master = self._create_master_weight(p) if use_master else None
        states = [self._get_state(n, p) for n in self._state_names]
        new_val, new_master, new_states = self._update(
            p._value, grad, master, states, lr, wd, self._global_step)
        p._value = new_val
        if use_master:
            self._accumulators["master_weight"][id(p)] = new_master
        for n, s in zip(self._state_names, new_states):
            self._set_state(n, p, s)

    def _update(self, value, grad, master, states, lr, wd, step):
        """Dispatch into the jitted rule; scalars ride as traced args so one
        executable serves every step and LR schedule value.  Donation updates
        param/state buffers in place in HBM except during jit state-discovery
        (the recorder holds references for rollback)."""
        rule = type(self)._jitted_rule(donate=_donation_safe())
        if _metrics._ENABLED:
            _M_DISPATCH.inc_key(_K_LEAF_UPDATE)
        lr = jnp.asarray(lr, jnp.float32)
        step = jnp.asarray(step, jnp.float32)
        return rule(value, grad, master, states, lr, wd, step)

    @classmethod
    @functools.cache
    def _jitted_rule(cls, donate: bool = False):
        def apply(value, grad, master, states, lr, wd, step):
            work = master if master is not None else value
            grad = grad.astype(work.dtype)
            new_work, new_states = cls._update_rule(work, grad, states, lr,
                                                    wd, step)
            if master is not None:
                return new_work.astype(value.dtype), new_work, new_states
            return new_work, None, new_states
        return jax.jit(apply, static_argnames=("wd",),
                       donate_argnums=(0, 2, 3) if donate else ())

    # ------------------------------------------------------------ misc
    def clear_grad(self, set_to_zero: bool = True):
        for group in self._param_groups:
            for p in group["params"]:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import default_main_program, in_static_build
        if in_static_build():
            # building a paddle.static Program: record the update for
            # Executor.run instead of mutating params with build-time zeros
            default_main_program().record_minimize(self, loss)
            return None, None
        if loss._grad_node is not None or not loss.stop_gradient:
            loss.backward()
        self.step()
        return None, None

    @property
    def _parameter_list(self):
        out = []
        for g in self._param_groups:
            out.extend(g["params"])
        return out

    def state_dict(self):
        # keys follow the reference format: "<param_name>_<accumulator>"
        # (`python/paddle/optimizer/optimizer.py` keys accumulators by the
        # parameter's name) so checkpoints survive parameter reordering
        import warnings
        gs = self._global_step
        if not isinstance(gs, int):
            # fused scaler steps keep the applied-step count on device
            # (it is found_inf-dependent); checkpointing materializes it
            try:
                gs = int(gs)
            except TypeError:  # tracer during capture: keep as-is
                pass
        out = {"LR_Scheduler": self._lr.state_dict()
               if isinstance(self._lr, LRScheduler) else {},
               "global_step": gs}
        for name, store in self._accumulators.items():
            for p in self._parameter_list:
                if id(p) in store:
                    key = f"{p.name}_{name}"
                    if key in out:
                        warnings.warn(
                            f"optimizer.state_dict: duplicate parameter "
                            f"name {p.name!r}; state for one of them is "
                            f"overwritten — give parameters unique names")
                    out[key] = Tensor._wrap(store[id(p)])
        return out

    def _known_state_names(self):
        names = set(self._state_names) | set(self._accumulators)
        names.add("master_weight")
        return names

    def remap_state_keys(self, network, sd, to_structured: bool):
        """Translate accumulator keys between this process's auto-generated
        parameter names ("param_37_moment1") and the network's stable
        structured names ("fc.0.weight@moment1"), so a .pdopt saved by one
        process restores into a freshly built model (the reference keys by
        parameter name, which its framework keeps stable across processes;
        our names are a process-global counter, so checkpoints store the
        structured form)."""
        state = network.state_dict()
        by_pname = {p.name: k for k, p in state.items()}
        accs = self._known_state_names()
        out = {}
        for key, v in sd.items():
            if key in ("LR_Scheduler", "global_step"):
                out[key] = v
                continue
            mapped = None
            if to_structured:
                for acc in accs:
                    if key.endswith("_" + acc):
                        sname = by_pname.get(key[:-len(acc) - 1])
                        if sname is not None:
                            mapped = f"{sname}@{acc}"
                        break
            elif "@" in key:
                sname, acc = key.rsplit("@", 1)
                p = state.get(sname)
                if p is not None:
                    mapped = f"{p.name}_{acc}"
            out[mapped or key] = v
        return out

    def set_state_dict(self, state):
        import warnings
        import numpy as np
        if isinstance(self._lr, LRScheduler) and state.get("LR_Scheduler"):
            self._lr.set_state_dict(state["LR_Scheduler"])
        self._global_step = int(state.get("global_step", 0))
        params = self._parameter_list
        by_name = {p.name: p for p in params}
        accs = self._known_state_names()
        for key, v in state.items():
            if key in ("LR_Scheduler", "global_step"):
                continue
            val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            # name-keyed format: "<param_name>_<accumulator>"; exact match on
            # both halves so a param name that prefixes another can't steal
            # its state, and unknown accumulators aren't silently created
            matched = False
            for acc in accs:
                if key.endswith("_" + acc):
                    p = by_name.get(key[:-len(acc) - 1])
                    if p is not None:
                        if tuple(val.shape) != tuple(p.shape):
                            warnings.warn(
                                f"optimizer.set_state_dict: {key!r} shape "
                                f"{tuple(val.shape)} does not match param "
                                f"{p.name} shape {tuple(p.shape)}; skipping")
                            matched = True
                            break
                        self._accumulators.setdefault(acc, {})[id(p)] = val
                        matched = True
                        break
            if matched:
                continue
            # legacy positional format: "<accumulator>_<index>"
            name, _, idx = key.rpartition("_")
            try:
                p = params[int(idx)]
            except (ValueError, IndexError):
                warnings.warn(
                    f"optimizer.set_state_dict: unmatched key {key!r} "
                    f"(no parameter/accumulator for it); skipping")
                continue
            self._accumulators.setdefault(name, {})[id(p)] = val


class SGD(Optimizer):
    _state_names: List[str] = []

    @staticmethod
    def _update_rule(w, g, states, lr, wd, step):
        if wd:
            g = g + wd * w
        return w - lr * g, []


class Momentum(Optimizer):
    _state_names = ["velocity"]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov
        # per-instance rule (momentum is a python constant baked into jit)
        mu = float(momentum)
        nesterov = bool(use_nesterov)

        def rule(w, g, states, lr, wd, step):
            (v,) = states
            if wd:
                g = g + wd * w
            v2 = mu * v + g
            if nesterov:
                return w - lr * (g + mu * v2), [v2]
            return w - lr * v2, [v2]
        self._update_rule = staticmethod(rule)
        self.__rule_jit = None

    def _update(self, value, grad, master, states, lr, wd, step):
        return _instance_update(self, self._update_rule.__func__, value, grad,
                                master, states, lr, wd, step)


class _AdamBase(Optimizer):
    _state_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, decoupled: bool = False,
                 apply_decay_param_fun=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = float(beta1 if not isinstance(beta1, Tensor) else beta1.item())
        self._beta2 = float(beta2 if not isinstance(beta2, Tensor) else beta2.item())
        self._epsilon = float(epsilon)
        self._decoupled = decoupled
        self._apply_decay_param_fun = apply_decay_param_fun
        b1, b2, eps, dec = self._beta1, self._beta2, self._epsilon, decoupled

        def rule(w, g, states, lr, wd, step):
            m, v = states
            if wd and not dec:
                g = g + wd * w
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * jnp.square(g)
            mhat = m2 / (1 - b1 ** step)
            vhat = v2 / (1 - b2 ** step)
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if wd and dec:
                upd = upd + wd * w
            return w - lr * upd, [m2, v2]
        self._rule = rule
        self._rule_jit = None

    def _apply_one(self, p, grad, lr, wd, l1=0.0):
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        super()._apply_one(p, grad, lr, wd, l1)

    def _update(self, value, grad, master, states, lr, wd, step):
        return _instance_update(self, self._rule, value, grad, master, states,
                                lr, wd, step)


class Adam(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, decoupled=False)


class AdamW(_AdamBase):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, decoupled=True,
                         apply_decay_param_fun=apply_decay_param_fun)


class Adagrad(Optimizer):
    _state_names = ["moment"]

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        eps = float(epsilon)

        def rule(w, g, states, lr, wd, step):
            (acc,) = states
            if wd:
                g = g + wd * w
            acc2 = acc + jnp.square(g)
            return w - lr * g / (jnp.sqrt(acc2) + eps), [acc2]
        self._rule = rule
        self._rule_jit = None

    _update = _AdamBase._update


class RMSProp(Optimizer):
    _state_names = ["mean_square", "mean_grad", "momentum_acc"]

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        rho_, eps, mu, cent = float(rho), float(epsilon), float(momentum), centered

        def rule(w, g, states, lr, wd, step):
            ms, mg, mom = states
            if wd:
                g = g + wd * w
            ms2 = rho_ * ms + (1 - rho_) * jnp.square(g)
            if cent:
                mg2 = rho_ * mg + (1 - rho_) * g
                denom = jnp.sqrt(ms2 - jnp.square(mg2) + eps)
            else:
                mg2 = mg
                denom = jnp.sqrt(ms2 + eps)
            mom2 = mu * mom + lr * g / denom
            return w - mom2, [ms2, mg2, mom2]
        self._rule = rule
        self._rule_jit = None

    _update = _AdamBase._update


class Adadelta(Optimizer):
    _state_names = ["avg_squared_grad", "avg_squared_update"]

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        rho_, eps = float(rho), float(epsilon)

        def rule(w, g, states, lr, wd, step):
            ag, au = states
            if wd:
                g = g + wd * w
            ag2 = rho_ * ag + (1 - rho_) * jnp.square(g)
            upd = jnp.sqrt(au + eps) / jnp.sqrt(ag2 + eps) * g
            au2 = rho_ * au + (1 - rho_) * jnp.square(upd)
            return w - lr * upd, [ag2, au2]
        self._rule = rule
        self._rule_jit = None

    _update = _AdamBase._update


class Adamax(Optimizer):
    _state_names = ["moment", "inf_norm"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        b1, b2, eps = float(beta1), float(beta2), float(epsilon)

        def rule(w, g, states, lr, wd, step):
            m, u = states
            if wd:
                g = g + wd * w
            m2 = b1 * m + (1 - b1) * g
            u2 = jnp.maximum(b2 * u, jnp.abs(g))
            return w - lr / (1 - b1 ** step) * m2 / (u2 + eps), [m2, u2]
        self._rule = rule
        self._rule_jit = None

    _update = _AdamBase._update


class Lamb(Optimizer):
    _state_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name, multi_precision)
        b1, b2, eps = float(beta1), float(beta2), float(epsilon)
        self._exclude_fn = exclude_from_weight_decay_fn

        def rule(w, g, states, lr, wd, step):
            m, v = states
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * jnp.square(g)
            mhat = m2 / (1 - b1 ** step)
            vhat = v2 / (1 - b2 ** step)
            r = mhat / (jnp.sqrt(vhat) + eps)
            if wd:
                r = r + wd * w
            w_norm = jnp.linalg.norm(w)
            r_norm = jnp.linalg.norm(r)
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return w - lr * trust * r, [m2, v2]
        self._rule = rule
        self._rule_jit = None

    def _apply_one(self, p, grad, lr, wd, l1=0.0):
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        super()._apply_one(p, grad, lr, wd, l1)

    _update = _AdamBase._update
