"""Device management.

Analogue of the reference's DeviceManager/place system
(`paddle/phi/backends/device_manager.h:134`, `phi/common/place.h`): enumerate
devices, select a current device, and expose Place-like handles.  On TPU the
"device" is a PJRT device obtained from JAX; multi-chip topology is expressed
through `jax.sharding.Mesh` (see paddle_tpu.distributed), not through per-place
streams.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CustomPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_tpu", "current_jax_device",
]


class Place:
    """A device handle, equivalent to phi::Place."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind(d) == self.device_type]
        if not devs:
            # Fall back to any-platform lookup (e.g. "cpu" when only cpu exists).
            devs = jax.devices(self.device_type) if self.device_type in (
                "cpu", "tpu", "gpu") else jax.devices()
        return devs[self.device_id]


def CPUPlace(device_id: int = 0) -> Place:
    return Place("cpu", device_id)


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


def CustomPlace(device_type: str, device_id: int = 0) -> Place:
    return Place(device_type, device_id)


def _kind(d: jax.Device) -> str:
    plat = d.platform
    # Some PJRT plugins (e.g. the axon tunnel) report their own platform name;
    # normalize anything TPU-like to "tpu".
    if "tpu" in plat or "axon" in plat:
        return "tpu"
    return plat


_lock = threading.RLock()
_current: Optional[Place] = None


def get_all_devices() -> List[str]:
    return [f"{_kind(d)}:{d.id}" for d in jax.devices()]


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return len(jax.devices())
    return len([d for d in jax.devices() if _kind(d) == device_type])


def is_compiled_with_tpu() -> bool:
    try:
        return any(_kind(d) == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def set_device(device: str | Place) -> Place:
    """Select the current device, e.g. ``set_device("tpu:0")``."""
    global _current
    if isinstance(device, str):
        if ":" in device:
            kind, idx = device.split(":", 1)
            place = Place(kind, int(idx))
        else:
            place = Place(device, 0)
    else:
        place = device
    with _lock:
        _current = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    global _current
    with _lock:
        if _current is None:
            d = jax.devices()[0]
            _current = Place(_kind(d), 0)
        return _current


def current_jax_device() -> jax.Device:
    return current_place().jax_device
