"""Metric base class and the standard classification metrics.

Parity: `python/paddle/metric/metrics.py` — Metric (`:34`, the
reset/update/accumulate/compute protocol), Accuracy (`:183`), Precision
(`:333`), Recall (`:462`), Auc (`:594`), functional accuracy (`:772`).

TPU-native split of labor: `compute()` runs on device (jnp, fusable into a
jitted eval step and cheap to transfer — e.g. Accuracy.compute returns a
small correct/top-k boolean block), `update()` accumulates on host numpy
between steps.  This mirrors the reference's design intent (compute on the
device graph, update in Python) rather than its implementation.
"""

from __future__ import annotations

import abc
from typing import Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x) -> np.ndarray:
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    return np.asarray(x)


class Metric(metaclass=abc.ABCMeta):
    """Stateful streaming metric: reset() -> update()* -> accumulate()."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Device-side preprocessing of (pred, label); defaults to identity.

        Whatever this returns is passed (as host arrays) to `update`.
        """
        return args


class Accuracy(Metric):
    """Top-k accuracy.  Parity: `metrics.py:183`."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name=None,
                 *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._init_name(name)
        self.reset()

    def compute(self, pred, label, *args):
        """Per-sample correctness of the top-maxk predictions (device)."""
        p = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
        l = label._value if isinstance(label, Tensor) else jnp.asarray(label)
        idx = jnp.argsort(p, axis=-1)[..., ::-1][..., :self.maxk]
        if l.ndim == p.ndim:  # one-hot / column labels
            l = jnp.argmax(l, axis=-1) if l.shape[-1] == p.shape[-1] \
                else l.squeeze(-1)
        return (idx == l[..., None]).astype(jnp.float32)

    def update(self, correct, *args):
        c = _to_np(correct)
        num = c.shape[0] if c.ndim else 1
        self.total_samples += num
        for i, k in enumerate(self.topk):
            self.correct_k[i] += float(c[..., :k].sum())
        res = [ck / max(self.total_samples, 1) for ck in self.correct_k]
        return res if len(self.topk) > 1 else res[0]

    def reset(self):
        self.total_samples = 0
        self.correct_k = [0.0 for _ in self.topk]

    def accumulate(self):
        res = [ck / max(self.total_samples, 1) for ck in self.correct_k]
        return res if len(res) > 1 else res[0]

    def _init_name(self, name):
        name = name or "acc"
        self._name = [f"{name}_top{k}" for k in self.topk] \
            if len(self.topk) > 1 else [name]

    def name(self):
        return self._name


class Precision(Metric):
    """Binary precision = tp / (tp + fp).  Parity: `metrics.py:333`."""

    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_to_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall = tp / (tp + fn).  Parity: `metrics.py:462`."""

    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_to_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via histogram buckets.  Parity: `metrics.py:594`."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        """preds: (N, 2) class probabilities or (N,) positive scores."""
        p = _to_np(preds)
        pos_prob = p[:, 1] if p.ndim == 2 else p.reshape(-1)
        l = _to_np(labels).reshape(-1).astype(np.int64)
        bucket = np.clip((pos_prob * self._num_thresholds).astype(np.int64),
                         0, self._num_thresholds)
        np.add.at(self._stat_pos, bucket, (l == 1).astype(np.int64))
        np.add.at(self._stat_neg, bucket, (l == 0).astype(np.int64))

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        # walk thresholds from high to low, accumulating TP/FP counts
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += self.trapezoid_area(tot_neg, new_neg, tot_pos, new_pos)
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy.  Parity: `metrics.py:772`."""
    p = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    l = label._value if isinstance(label, Tensor) else jnp.asarray(label)
    idx = jnp.argsort(p, axis=-1)[..., ::-1][..., :k]
    if l.ndim == p.ndim:
        l = l.squeeze(-1)
    hit = (idx == l[..., None]).any(axis=-1)
    return Tensor._wrap(jnp.mean(hit.astype(jnp.float32)))
