"""Runtime observability: metrics registry, trace spans, step telemetry,
flight recorder, perf-evidence harness.

Parts (ISSUE 1 + ISSUE 2 tentpoles):

* :mod:`.metrics` — process-wide Counter / Gauge / Histogram registry
  with labels; ``snapshot()`` / ``export_json()`` for readout, flag-gated
  (``FLAGS_enable_metrics``) so disabled instruments cost one boolean
  check.
* :func:`span` — user-labelled timing span.  Always observed into the
  ``spans.seconds`` histogram; when a :class:`paddle_tpu.profiler.Profiler`
  is recording, the span also lands on the host timeline (the existing
  ``_HostTracer``), so spans show up in exported chrome traces next to
  per-op dispatch events.
* :mod:`.telemetry` — per-training-step :class:`~.telemetry.StepTimeline`
  records (wall/compile/comm split, compute/comm/host fractions,
  tokens/sec, MFU via the shared :mod:`.flops` helper).
* :mod:`.flight_recorder` — bounded ring of the last K step records +
  events, dumped to JSON on demand, on an unhandled train-step
  exception, or when the NaN/Inf watchdog
  (``FLAGS_enable_nan_watchdog``) trips.  CLI:
  ``python -m paddle_tpu.observability.dump``.
* :mod:`.flops` — the ONE FLOPs/MFU accounting helper (models, the
  auto-tuner cost model, bench and telemetry all use it).
* :mod:`.harness` — registered benchmark rungs with backend probing and
  degradation: every rung always emits a schema-stable JSON record
  ``{rung, ok, value|error, device, elapsed_s}`` instead of a run-killing
  stack trace (`bench.py` drives it).

Usage::

    from paddle_tpu import observability as obs

    tl = obs.telemetry.StepTimeline(flops_per_token=fpt,
                                    device_kind="tpu v5e")
    with tl.step(tokens=B * S) as st:
        loss = step(x, y)
    st.annotate(loss=float(loss))
    tl.summary()                            # fractions, tokens/s, MFU

    obs.metrics.snapshot()                  # dict of every live metric
    obs.metrics.export_json("metrics.json")
"""

from __future__ import annotations

import time
from typing import Optional

from . import metrics  # noqa: F401
from . import descriptions  # noqa: F401
from . import flops  # noqa: F401
from . import flight_recorder  # noqa: F401
from . import telemetry  # noqa: F401
from . import quantiles  # noqa: F401
from . import compile_tracker  # noqa: F401
from . import xray  # noqa: F401
from .metrics import (  # noqa: F401
    counter, gauge, histogram, quantile, snapshot, reset, export_json,
)

__all__ = ["metrics", "harness", "span", "telemetry", "flight_recorder",
           "flops", "quantiles", "compile_tracker", "xray", "chrome",
           "descriptions", "export", "http",
           "counter", "gauge", "histogram", "quantile", "snapshot",
           "reset", "export_json"]

_SPAN_SECONDS = metrics.histogram(
    "spans.seconds", "wall time of observability.span regions")


class span:
    """Timing span: context manager (or begin()/end()) that records wall
    time into the ``spans.seconds`` histogram (labelled by name) and, when
    a Profiler is recording, onto the host chrome-trace timeline."""

    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._t0: Optional[float] = None

    def begin(self) -> "span":
        self._t0 = time.perf_counter()
        return self

    def end(self) -> Optional[float]:
        if self._t0 is None:
            return None
        t0, self._t0 = self._t0, None
        t1 = time.perf_counter()
        _SPAN_SECONDS.observe(t1 - t0, name=self.name)
        from ..profiler import profiler as _prof
        tracer = _prof.active_tracer()
        if tracer is not None:
            tracer.add(self.name, t0, t1, category="span")
        return t1 - t0

    def __enter__(self) -> "span":
        return self.begin()

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


def __getattr__(name):
    # leaf modules only bench/test/scrape flows need; kept lazy so
    # `import paddle_tpu` never pays for them
    if name in ("harness", "export", "http", "chrome"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
