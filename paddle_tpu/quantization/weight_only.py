"""Weight-only int8 quantization for the inference path.

Parity seat: the reference's weight-only quantized inference ops
(`paddle/phi/kernels/fusion/gpu/fused_weight_only_linear_pass` family,
AWQ/GPTQ-style deployment in PaddleNLP): matmul weights are stored as
int8 with per-output-channel absmax scales and dequantized inside the
compiled matmul, trading a cheap elementwise multiply for ~4x less
weight memory (fp32 baseline; the reference counts ~2x from fp16).

TPU-native shape: quantization happens ONCE at engine weight-snapshot
time (host side); the int8 tensor + scale ride into the compiled
program as inputs, and `dequantize_int8` runs INSIDE the traced
program, so XLA fuses the scale multiply into the consumer matmul and
device weight residency is int8.

The per-channel contract that makes tensor-parallel slicing safe:
scales keep their reduced axis (``keepdims=True``), so a scale tensor
has exactly the weight's rank with size 1 on the reduction axis.
Because every channel is quantized independently, slicing along any
NON-reduced axis commutes with quantization bit-for-bit:
``quantize(w)[..., s]  ==  quantize(w[..., s])`` — which is why a TP
plan can quantize first and shard after (inference/quant.py) and still
be bit-identical to a rank-local quantization.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["quantize_absmax_int8", "dequantize_int8", "QMAX"]

QMAX = 127  # symmetric int8: the -128 code is never produced


def quantize_absmax_int8(w, axis: int = 0):
    """Per-channel symmetric absmax int8 over the ``axis`` dimension
    (the matmul contraction axis, so each OUTPUT channel owns a scale).

    Returns ``(q, scale)``: ``q`` int8 with ``w``'s shape, ``scale``
    ``w``'s dtype with ``shape[axis] == 1`` (keepdims).  All-zero
    channels quantize to zeros with scale 1 (dequant stays exact).
    """
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / QMAX, 1).astype(w.dtype)
    q = jnp.clip(jnp.round(w / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale):
    """``q * scale`` back in the scale's (original weight) dtype; traced
    inside compiled programs so XLA fuses it into the consuming matmul."""
    return (q.astype(scale.dtype) * scale)
