"""Probability distributions.  Parity: `python/paddle/distribution/`."""

from .distribution import Distribution
from .distributions import (Bernoulli, Beta, Categorical, Dirichlet,
                            Exponential, Gamma, Geometric, Gumbel, Laplace,
                            LogNormal, Multinomial, Normal, Poisson, Uniform)
from .extras import (AbsTransform, AffineTransform, Binomial, Cauchy,
                     ChainTransform, ContinuousBernoulli, ExpTransform,
                     Independent, MultivariateNormal, PowerTransform,
                     SigmoidTransform, TanhTransform, Transform,
                     TransformedDistribution)
from .kl import kl_divergence, register_kl

__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Dirichlet", "Gamma", "Laplace", "Exponential",
           "LogNormal", "Gumbel", "Geometric", "Poisson", "Multinomial",
           "Binomial", "Cauchy", "ContinuousBernoulli",
           "MultivariateNormal", "Independent", "TransformedDistribution",
           "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
           "SigmoidTransform", "TanhTransform", "AbsTransform",
           "ChainTransform", "kl_divergence", "register_kl"]
