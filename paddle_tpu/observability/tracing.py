"""Cross-process distributed tracing for the replica fleet (ISSUE 17).

One request that crosses router -> replica -> prefix-cache handoff used
to leave three disconnected flight-recorder fragments with no shared id
and no shared clock.  This module supplies the three missing pieces:

* **trace context** — a 16-hex ``trace_id`` minted once per ``/generate``
  plus an 8-hex per-hop ``span_id``, carried on the wire as the
  ``X-Graft-Trace: <trace_id>-<span_id>`` header and threaded into
  ``Request`` objects so every lifecycle / flight / handoff record tags
  itself with the same id;

* **clock alignment** — :class:`ClockSync` estimates a remote process's
  clock offset from a ``/healthz`` round-trip (the reply embeds the
  server's ``unix_time``).  The estimate is ``server_time - midpoint``
  of the round-trip with error bound ``rtt / 2``; the minimum-RTT sample
  wins, the classic NTP-style filter;

* **timeline merge** — :func:`fleet_trace` folds one flight dump per
  process into a single chrome://tracing document: each process becomes
  its own ``pid`` row group, replica clocks are shifted into router time
  using the recorded ``clock_sync`` events, and every span keeps its
  ``trace_id`` so chrome's flow highlighting follows one request across
  router, prefill engine and decode engine.

Everything here is stdlib-only and runs identically with metrics
disabled: minting an id is two ``os.urandom`` calls, and the header
parse is a regex match.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import chrome as _chrome

TRACE_HEADER = "X-Graft-Trace"

_TRACE_RE = re.compile(r"^[0-9a-f]{8,32}$")
_SPAN_RE = re.compile(r"^[0-9a-f]{4,16}$")


def mint_trace_id() -> str:
    """A fresh 16-hex trace id (64 random bits)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 8-hex span id (32 random bits)."""
    return os.urandom(4).hex()


def format_header(trace_id: str, span_id: Optional[str] = None) -> str:
    """Wire form of a trace context: ``trace_id`` or ``trace_id-span``."""
    if span_id:
        return f"{trace_id}-{span_id}"
    return trace_id


def parse_header(value: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """Parse an ``X-Graft-Trace`` header into ``(trace_id, parent_span)``.

    Accepts ``<trace>`` or ``<trace>-<span>`` where trace is 8-32 lowercase
    hex chars and span 4-16.  Anything malformed yields ``(None, None)`` —
    a bad header must never break request handling.
    """
    if not value or not isinstance(value, str):
        return None, None
    value = value.strip().lower()
    trace, sep, span = value.partition("-")
    if not _TRACE_RE.match(trace):
        return None, None
    if not sep:
        return trace, None
    if not _SPAN_RE.match(span):
        return trace, None
    return trace, span


class ClockSync:
    """Minimum-RTT clock-offset estimate for one remote process.

    ``update(t0, server_unix, t1)`` feeds one round-trip: local send time
    ``t0``, the server's self-reported ``unix_time``, local receive time
    ``t1``.  The offset estimate is ``server_unix - (t0 + t1) / 2`` and
    its error is bounded by half the round-trip; the sample with the
    smallest RTT is kept because its bound is tightest.
    """

    __slots__ = ("offset_s", "err_s", "rtt_s")

    def __init__(self) -> None:
        self.offset_s: Optional[float] = None
        self.err_s: Optional[float] = None
        self.rtt_s: Optional[float] = None

    def update(self, t0: float, server_unix: float, t1: float) -> bool:
        """Feed one round-trip; returns True if the estimate improved."""
        rtt = t1 - t0
        if rtt < 0:
            return False
        if self.rtt_s is not None and rtt >= self.rtt_s:
            return False
        self.rtt_s = rtt
        self.offset_s = server_unix - (t0 + t1) / 2.0
        self.err_s = rtt / 2.0
        return True

    def view(self) -> Dict[str, Optional[float]]:
        return {"offset_s": self.offset_s, "err_s": self.err_s,
                "rtt_s": self.rtt_s}


# ------------------------------------------------------- timeline merge


def _doc_process_name(doc: Dict[str, Any], fallback: str) -> str:
    """A flight doc self-identifies via its ``replica_meta`` event."""
    for ev in doc.get("events", ()):
        if ev.get("kind") == "replica_meta" and ev.get("replica"):
            return str(ev["replica"])
    return fallback


def _collect_offsets(docs: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """Per-process clock offsets from ``clock_sync`` events.

    The router records one ``clock_sync`` event per replica poll with
    ``{replica, offset_s, err_s, rtt_s}``; the smallest-error estimate
    per replica wins (same min-RTT rule as :class:`ClockSync`).
    """
    best: Dict[str, Tuple[float, float]] = {}
    for doc in docs:
        for ev in doc.get("events", ()):
            if ev.get("kind") != "clock_sync":
                continue
            name = ev.get("replica")
            off = ev.get("offset_s")
            if name is None or off is None:
                continue
            err = float(ev.get("err_s") or 0.0)
            cur = best.get(name)
            if cur is None or err < cur[1]:
                best[str(name)] = (float(off), err)
    return {k: v[0] for k, v in best.items()}


def _collect_trace_ids(doc: Dict[str, Any]) -> List[str]:
    seen: List[str] = []
    for rec in list(doc.get("events", ())) + list(doc.get("steps", ())):
        tid = rec.get("trace_id")
        if tid and tid not in seen:
            seen.append(tid)
    return seen


def fleet_trace(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge flight dumps from several processes into one chrome trace.

    Each doc becomes its own chrome process (pid = position + 1) named
    after its ``replica_meta`` event (falling back to ``proc<i>``).
    Replica clocks are shifted into the first doc's (router's) timebase
    by subtracting the recorded ``clock_sync`` offset — the router
    measured ``offset = replica_clock - router_clock``, so replica
    timestamps move by ``-offset``.
    """
    offsets = _collect_offsets(docs)
    merged: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    trace_ids: List[str] = []
    for i, doc in enumerate(docs):
        name = _doc_process_name(doc, f"proc{i}")
        off = offsets.get(name, 0.0)
        sub = _chrome.trace_from_flight(doc, pid=i + 1,
                                        clock_offset_s=-off,
                                        process_name=name)
        merged.extend(sub["traceEvents"])
        processes.append({"pid": i + 1, "name": name,
                          "clock_offset_s": round(off, 6),
                          "source_pid": doc.get("pid")})
        for tid in _collect_trace_ids(doc):
            if tid not in trace_ids:
                trace_ids.append(tid)
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": "paddle_tpu.fleet_trace/v1",
            "processes": processes,
            "trace_ids": trace_ids,
        },
    }
