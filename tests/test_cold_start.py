"""Cold-start subsystem (ISSUE 7): persistent compilation cache
(`core/compile_cache.py`), the serving pad-bucket ladder, and
`ServingEngine.warmup()`.

The acceptance story: a warm restart reads executables from
FLAGS_compilation_cache_dir instead of recompiling, and a warmed
serving engine triggers ZERO compile-tracker events once traffic runs —
every program the engine can dispatch was enumerated from the ONE
pad-bucket ladder and compiled up front.
"""

import os

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import compile_cache
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability import compile_tracker
from paddle_tpu.observability import metrics as obs_metrics


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


# ------------------------------------------------------ persistent cache

def test_flag_applies_and_detaches_cache_dir(tmp_path):
    """FLAGS_compilation_cache_dir drives jax_compilation_cache_dir via
    the on_change hook, and restoring the flag detaches it again."""
    d = str(tmp_path / "cache")
    assert not compile_cache.is_enabled()
    with flag_guard(compilation_cache_dir=d):
        assert compile_cache.is_enabled()
        applied = compile_cache.active_dir()
        assert applied == os.path.abspath(d) and os.path.isdir(applied)
        assert jax.config.jax_compilation_cache_dir == applied
    assert not compile_cache.is_enabled()
    assert jax.config.jax_compilation_cache_dir is None


def test_enable_flag_gates_the_dir(tmp_path):
    """FLAGS_enable_compilation_cache=0 keeps the dir flag inert."""
    with flag_guard(enable_compilation_cache=False,
                    compilation_cache_dir=str(tmp_path / "c2")):
        assert not compile_cache.is_enabled()
        assert jax.config.jax_compilation_cache_dir is None
    assert not compile_cache.is_enabled()


def test_cache_hits_misses_counters_report_and_prometheus(tmp_path):
    """A fresh dir takes misses, a cleared in-process cache then HITS
    the persistent entries; both are visible as registry counters, in
    the Prometheus rendering (compile_cache_{hits,misses}_total), and in
    compile_report()['persistent_cache'] with a hit ratio."""
    from paddle_tpu.observability.export import render_prometheus
    hits = obs_metrics.get("compile.cache_hits_total")
    misses = obs_metrics.get("compile.cache_misses_total")
    h0, m0 = hits.total(), misses.total()
    with flag_guard(compilation_cache_dir=str(tmp_path / "c3")):
        x = paddle.to_tensor(np.ones((37, 41), np.float32))
        np.asarray((x @ x.T).sum()._value)
        assert misses.total() > m0          # fresh dir: compiles missed
        rep = compile_cache.cache_report()
        assert rep["enabled"] and rep["entries"] > 0 and rep["bytes"] > 0
        jax.clear_caches()                  # drop in-process executables
        np.asarray((x @ x.T).sum()._value)
        assert hits.total() > h0            # ...and reload from disk
        rep = compile_cache.cache_report()
        assert rep["hits"] > 0 and 0.0 < rep["hit_ratio"] <= 1.0
        text = render_prometheus()
        assert "compile_cache_hits_total" in text
        assert "compile_cache_misses_total" in text
    full = compile_tracker.compile_report()
    assert "persistent_cache" in full
    assert set(full["persistent_cache"]) >= {
        "enabled", "dir", "hits", "misses", "hit_ratio", "entries",
        "bytes"}


def test_autotune_kernel_enable_routes_through_compile_cache(tmp_path):
    """ISSUE 7 satellite: incubate.autotune no longer owns a private
    hard-coded cache dir — kernel.enable applies the flag-configured
    dir through core/compile_cache and reports it in get_config()."""
    from paddle_tpu.incubate import autotune
    d = str(tmp_path / "tune")
    with flag_guard(compilation_cache_dir=d):
        autotune.set_config({"kernel": {"enable": True}})
        cfg = autotune.get_config()
        assert cfg["kernel"]["cache_dir"] == os.path.abspath(d)
        assert jax.config.jax_compilation_cache_dir == os.path.abspath(d)
    assert jax.config.jax_compilation_cache_dir is None


# ---------------------------------------------------------- ladder rules

def test_default_ladder_matches_legacy_pow2(model):
    """With the flag unset the materialized ladder reproduces the legacy
    min(power-of-two, block-table) formula bucket for bucket."""
    eng = ServingEngine(model, max_batch=2, max_context=96, block_size=16)
    assert eng.pad_ladder == (16, 32, 64, 96)
    cap = eng.nb_per_seq * eng.bs
    for L in range(1, 97):
        b = 16
        while b < L:
            b *= 2
        assert eng._pad_bucket(L) == min(b, cap), L


def test_custom_ladder_clamps_sorts_and_validates(model):
    eng = ServingEngine(model, max_batch=2, max_context=96,
                        block_size=16, pad_buckets="64, 16,32,1000")
    assert eng.pad_ladder == (16, 32, 64, 96)      # clamped + sorted
    eng = ServingEngine(model, max_batch=2, max_context=96,
                        block_size=16, pad_buckets=(20, 50))
    assert eng._pad_bucket(18) == 20               # non-pow2 rungs work
    assert eng._pad_bucket(21) == 50
    assert eng._pad_bucket(60) == 64               # beyond ladder: pow2
    with pytest.raises(ValueError, match="positive"):
        ServingEngine(model, max_batch=2, max_context=96,
                      block_size=16, pad_buckets="0,16")


def test_ladder_drives_worst_case_accounting(model):
    """add_request's worst-case block math uses the SAME ladder as
    admission padding: a bucket admitted here can never out-size the
    block table at prefill time."""
    with flag_guard(serving_pad_buckets="16,96"):
        eng = ServingEngine(model, max_batch=2, max_context=96,
                            block_size=16, num_blocks=6)
    # prompt 17 pads to bucket 96 -> 6 blocks now; growth 0 extra; fits
    # exactly.  Under the default ladder it would pad to 32 (2 blocks).
    r = eng.add_request(Request(np.arange(1, 18), max_new_tokens=4))
    eng.run()
    assert r.done and len(r.output_ids) == 4
    assert eng.stats()["free_blocks"] == 6


# -------------------------------------------------------------- warmup

def _drive_mixed_traffic(eng, vocab, lens, budget=7):
    rng = np.random.RandomState(11)
    reqs = []
    for i, L in enumerate(lens):
        kw = {} if i % 2 == 0 else dict(do_sample=True, temperature=0.9,
                                        top_k=30, seed=100 + i)
        reqs.append(eng.add_request(
            Request(rng.randint(1, vocab, (L,)), max_new_tokens=budget,
                    **kw)))
    eng.run()
    return reqs


@pytest.mark.slow  # 18s measured (PR 18 re-budget): warms the full bucket grid; test_ladder_drives_worst_case_accounting keeps the fast ladder pin and test_pallas_paged_kernels warms an engine fast
def test_warmup_grid_zero_compiles_then_one_blamed_outside(model):
    """THE acceptance test (ISSUE 7 satellite): after warmup, mixed
    greedy/sampled traffic across every pad bucket triggers zero
    compile-tracker events; a request OUTSIDE the ladder still works,
    at the price of exactly one compile blamed on the new L_pad."""
    vocab = model.cfg.vocab_size
    with flag_guard(serving_warmup=True, serving_pad_buckets="16,32,64"):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, steps_per_tick=2)
        info = eng.warmup()
        # 2 tick variants (k=2 + the k=1 tail; greedy and sampled share
        # each) + the host-sampling decode program + 3 prefill buckets
        # + (prefix cache, ISSUE 9) 3 suffix-prefill buckets + the CoW
        # block copy
        assert info["programs"] == 10
        assert [g["L_pad"] for g in info["grid"]
                if g["program"] == "prefill"] == [16, 32, 64]
        assert [g["L_pad"] for g in info["grid"]
                if g["program"] == "prefill_cont"] == [16, 32, 64]
        assert [g["program"] for g in info["grid"]].count("cow") == 1
        assert eng.warmup() is info                   # idempotent
        before = compile_tracker.total_compiles()
        # budgets of 7 = 1 prefill token + 2 full k=2 ticks + k=1 tails,
        # prompts span all three buckets, greedy and sampled mixed
        reqs = _drive_mixed_traffic(eng, vocab, (12, 20, 40, 60))
        assert compile_tracker.total_compiles() == before
        assert all(len(r.output_ids) == 7 for r in reqs)
        st = eng.stats()
        assert st["warmup"]["programs"] == 10
        assert st["warmup"]["warmup_s"] > 0
        assert st["pad_buckets"] == [16, 32, 64]
        # outside the ladder: prompt 70 -> pow2 fallback bucket 128
        rng = np.random.RandomState(12)
        r = eng.add_request(Request(rng.randint(1, vocab, (70,)),
                                    max_new_tokens=4))
        eng.run()
        assert r.done and len(r.output_ids) == 4
        assert compile_tracker.total_compiles() == before + 1
        ev = compile_tracker.compile_report()["recent_events"][-1]
        assert ev["fn"] == "serving.prefill"
        assert "L_pad" in ev["cause"] and "128" in ev["cause"]


@pytest.mark.slow   # 22.6s measured (PR 14 re-budget): serves three
                    # full engines; the AOT path itself stays pinned
                    # fast by the zero-compile grid tests
def test_warmup_fallback_parity_with_unwarmed(model):
    """warmup(aot=False) — the dummy-execution fallback — and the AOT
    path both serve token-for-token what an unwarmed engine serves."""
    vocab = model.cfg.vocab_size

    def serve(warm):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, steps_per_tick=2,
                            pad_buckets="16,32")
        if warm is not None:
            info = eng.warmup(aot=warm)
            assert info["aot_programs"] == (info["programs"] if warm
                                            else 0)
        reqs = _drive_mixed_traffic(eng, vocab, (12, 24))
        return [list(r.output_ids) for r in reqs]

    baseline = serve(None)
    assert serve(False) == baseline
    assert serve(True) == baseline


@pytest.mark.slow   # 17.9s measured (PR 14 re-budget): compiles the
                    # 11-program spec grid; the plain-grid zero-compile
                    # pin stays fast and the ngram/fp8 @slow twin
                    # covers the spec-grid variant
def test_warmup_grid_spec_quant_zero_compiles(model):
    """ISSUE 10 acceptance: with spec decode AND int8 quant on, the
    warmup grid gains exactly the spec tick (draft/verify programs:
    prefill/cont/cow absorb the draft writes without new programs) and
    mixed post-warmup traffic still triggers ZERO compile-tracker
    events."""
    paddle.seed(0)
    draft = GPTForCausalLM(gpt3_tiny())
    draft.eval()
    vocab = model.cfg.vocab_size
    # ISSUE 14: the pin extends to X-ray sampling — a synced probe is
    # wrapper-level accounting, so it must add ZERO programs/compiles
    with flag_guard(serving_warmup=True, serving_pad_buckets="16,32,64",
                    xray_sample_interval=2):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, steps_per_tick=2,
                            draft_model=draft, spec_decode=True,
                            spec_k=3, quant="int8")
        info = eng.warmup()
        # the 10-program prefix grid + the one spec tick
        assert info["programs"] == 11
        assert [g["program"] for g in info["grid"]].count("spec_tick") \
            == 1
        assert next(g for g in info["grid"]
                    if g["program"] == "spec_tick")["spec_k"] == 3
        before = compile_tracker.total_compiles()
        reqs = _drive_mixed_traffic(eng, vocab, (12, 20, 40, 60))
        assert compile_tracker.total_compiles() == before
        assert all(len(r.output_ids) == 7 for r in reqs)
        st = eng.stats()
        assert st["speculative"]["ticks"] > 0
        assert st["quant"]["mode"] == "int8"
        assert st["warmup"]["programs"] == 11


@pytest.mark.slow   # compiles a full warmup grid incl. 3 ladder rungs;
                    # tier-1 keeps only the legacy-grid pins fast
def test_warmup_grid_ngram_adaptive_fp8_zero_compiles(model):
    """ISSUE 13 acceptance: with model-free drafting + the adaptive-k
    ladder + fp8 weight-only ALL on, the warmup grid enumerates one
    hostdraft spec program per ladder rung (no draft model anywhere)
    and post-warmup traffic — including adaptive-k transitions under
    a repetitive workload — triggers ZERO compile-tracker events."""
    vocab = model.cfg.vocab_size
    with flag_guard(serving_warmup=True, serving_pad_buckets="16,32,64"):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, steps_per_tick=2,
                            spec_decode=True, spec_draft="ngram",
                            spec_adaptive=True, spec_k_ladder="2,4,8",
                            quant="fp8")
        info = eng.warmup()
        # the 10-program prefix grid + one spec tick per ladder rung
        assert info["programs"] == 13
        spec_rungs = [g for g in info["grid"]
                      if g["program"] == "spec_tick"]
        assert [g["spec_k"] for g in spec_rungs] == [2, 4, 8]
        assert all(g["draft"] == "ngram" for g in spec_rungs)
        before = compile_tracker.total_compiles()
        reqs = _drive_mixed_traffic(eng, vocab, (12, 20, 40, 60))
        # a repetitive stream ramps k up the ladder under traffic —
        # adaptation must step between WARMED programs only
        rng = np.random.RandomState(13)
        pat = list(rng.randint(1, vocab, (4,)))
        r = eng.add_request(Request(np.array(pat * 12),
                                    max_new_tokens=30))
        eng.run()
        assert compile_tracker.total_compiles() == before
        assert all(len(q.output_ids) == 7 for q in reqs)
        assert r.done and len(r.output_ids) == 30
        st = eng.stats()
        assert st["speculative"]["draft"] == "ngram"
        assert st["speculative"]["k_switches"] >= 1
        assert st["quant"]["mode"] == "fp8"
        assert st["warmup"]["programs"] == 13


@pytest.mark.slow   # compiles a second full warmup grid — tier-1's
                    # ~30s margin keeps only the legacy-grid pins fast
def test_warmup_grid_chunked_zero_compiles(model):
    """ISSUE 11 acceptance: with chunked prefill on, the warmup grid
    swaps the monolithic prefill programs for the suffix-prefill chunk
    programs (one per ladder bucket — chunk offsets are traced), and
    mixed post-warmup traffic spanning every bucket still triggers
    ZERO compile-tracker events."""
    vocab = model.cfg.vocab_size
    with flag_guard(serving_warmup=True, serving_pad_buckets="16,32,64",
                    serving_prefill_chunk=8):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, steps_per_tick=2)
        info = eng.warmup()
        # 2 tick variants + host-sampling decode + 3 prefill_cont
        # buckets + CoW (prefix cache on) — and NO monolithic prefill:
        # a chunked engine never dispatches it
        assert info["programs"] == 7
        assert [g["L_pad"] for g in info["grid"]
                if g["program"] == "prefill_cont"] == [16, 32, 64]
        assert not any(g["program"] == "prefill" for g in info["grid"])
        before = compile_tracker.total_compiles()
        reqs = _drive_mixed_traffic(eng, vocab, (12, 20, 40, 60))
        assert compile_tracker.total_compiles() == before
        assert all(len(r.output_ids) == 7 for r in reqs)
        assert eng.stats()["prefill_chunks"] > 0


@pytest.mark.slow  # 6s measured: warms both sampling variants; test_warmup_grid_zero_compiles keeps the fast zero-compile pin
def test_warmup_covers_both_sampling_variants(model):
    """The grid always includes the host-sampling decode program AND
    the device-sampling tick: FLAGS_serving_device_sampling is read
    live at every dispatch, so flipping it on a WARMED engine mid-run
    must not route traffic to an un-warmed program."""
    vocab = model.cfg.vocab_size
    with flag_guard(serving_pad_buckets="16,32"):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=1)
        info = eng.warmup()     # taken with device sampling ON
        assert [g["program"] for g in info["grid"]] == \
            ["tick", "decode", "prefill", "prefill",
             "prefill_cont", "prefill_cont", "cow"]
        before = compile_tracker.total_compiles()
        with flag_guard(serving_device_sampling=False):
            # sampled request on the host-sampling path -> decode program
            reqs = _drive_mixed_traffic(eng, vocab, (10, 20), budget=4)
        reqs += _drive_mixed_traffic(eng, vocab, (12,), budget=4)
        assert compile_tracker.total_compiles() == before
        assert all(len(r.output_ids) == 4 for r in reqs)
