"""Per-op SPMD rules: dims-mapping inference for eager DistTensor ops.

Parity: `paddle/phi/infermeta/spmd_rules/` — matmul.cc, elementwise.cc,
reduction.cc, reshape.cc, transpose.cc, embedding.cc, softmax.cc,
layer_norm.cc, cross_entropy_with_softmax.cc, concat.cc, split.cc,
flash_attention.cc, `rules.h` registry.

Representation matches the reference: a `DistAttr` is a dims_mapping
(tensor dim -> mesh dim, -1 replicated) plus the set of mesh dims the
value is partial (pending-sum) over.  A rule takes input attrs (+ op
attrs), resolves conflicts, and returns (inferred input attrs, output
attrs).  On TPU these rules serve the eager op-by-op path — inside jit,
GSPMD performs the same propagation in XLA; the library exists so eager
DistTensor ops place outputs deterministically (and tests can check the
reference's published rule semantics).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["DistAttr", "register_spmd_rule", "get_spmd_rule", "infer_spmd"]


class DistAttr:
    """dims_mapping + partial mesh-dim set (reference TensorDistAttr)."""

    def __init__(self, dims_mapping: Sequence[int],
                 partial_dims: Sequence[int] = ()):
        self.dims_mapping = list(dims_mapping)
        self.partial_dims = set(partial_dims)

    def __eq__(self, other):
        return (isinstance(other, DistAttr)
                and self.dims_mapping == other.dims_mapping
                and self.partial_dims == other.partial_dims)

    def __repr__(self):
        p = f", partial={sorted(self.partial_dims)}" if self.partial_dims \
            else ""
        return f"DistAttr({self.dims_mapping}{p})"

    @property
    def ndim(self):
        return len(self.dims_mapping)


_RULES: Dict[str, Callable] = {}


def register_spmd_rule(name):
    def deco(fn):
        _RULES[name] = fn
        return fn
    return deco


def get_spmd_rule(name: str) -> Callable:
    if name not in _RULES:
        raise KeyError(f"no SPMD rule registered for op {name!r}")
    return _RULES[name]


def infer_spmd(name: str, *attrs, **op_attrs):
    return get_spmd_rule(name)(*attrs, **op_attrs)


# ------------------------------------------------------------------ helpers
def _merge_dim(a: int, b: int) -> int:
    """Resolve one tensor-dim mapping across inputs: sharded wins over
    replicated; conflicting shards fall back to replicated (reference
    ShardingMergeForTensors semantics)."""
    if a == -1:
        return b
    if b == -1 or a == b:
        return a
    return -1


def _einsum_like(notations: List[str], attrs: List[DistAttr],
                 out_notation: str) -> Tuple[List[DistAttr], DistAttr]:
    """Generalized einsum rule: merge per-letter mesh mappings across
    inputs, map the output, mark contracted sharded letters partial.
    This is the reference's axes-notation machinery (matmul.cc builds
    'mk,kn->mn' and calls the same merge)."""
    letter_map: Dict[str, int] = {}
    for notation, attr in zip(notations, attrs):
        assert len(notation) == attr.ndim, (notation, attr)
        for ch, dm in zip(notation, attr.dims_mapping):
            letter_map[ch] = _merge_dim(letter_map.get(ch, -1), dm)
    # a mesh dim may back at most one letter: later conflicts replicate
    used: Dict[int, str] = {}
    for ch in sorted(letter_map):
        dm = letter_map[ch]
        if dm == -1:
            continue
        if dm in used and used[dm] != ch:
            letter_map[ch] = -1
        else:
            used[dm] = ch
    inferred_in = [
        DistAttr([letter_map[ch] for ch in notation])
        for notation in notations]
    out_partial = {letter_map[ch] for ch in letter_map
                   if ch not in out_notation and letter_map[ch] != -1}
    out = DistAttr([letter_map[ch] for ch in out_notation],
                   sorted(out_partial))
    return inferred_in, out


# -------------------------------------------------------------------- rules
@register_spmd_rule("matmul")
def matmul_rule(x: DistAttr, y: DistAttr, trans_x=False, trans_y=False):
    """Parity: `spmd_rules/matmul.cc` (batched, broadcast, transposes)."""
    nx, ny = x.ndim, y.ndim
    batch = max(nx - 2, ny - 2, 0)
    letters = "abcdefgh"[:batch]
    xn = "mk" if not trans_x else "km"
    yn = "kn" if not trans_y else "nk"
    if nx == 1:
        xn = "k"
    if ny == 1:
        yn = "k"
    x_not = letters[batch - (nx - 2):] + xn if nx > 2 else xn
    y_not = letters[batch - (ny - 2):] + yn if ny > 2 else yn
    out_not = letters + ("m" if "m" in xn and nx > 1 else "") + \
        ("n" if "n" in yn and ny > 1 else "")
    (xi, yi), out = _einsum_like([x_not, y_not], [x, y], out_not)
    return [xi, yi], out


@register_spmd_rule("elementwise")
def elementwise_rule(*attrs: DistAttr):
    """Parity: `spmd_rules/elementwise.cc` — right-aligned broadcasting."""
    ndim = max(a.ndim for a in attrs)
    merged = [-1] * ndim
    for a in attrs:
        off = ndim - a.ndim
        for i, dm in enumerate(a.dims_mapping):
            merged[off + i] = _merge_dim(merged[off + i], dm)
    # a partial dim survives only when EVERY input is partial over it —
    # add(A_partial, B_full) resolved later would sum n copies of B;
    # mixed inputs must resolve first (their inferred attr drops the dim)
    common = None
    for a in attrs:
        common = set(a.partial_dims) if common is None \
            else common & a.partial_dims
    common = common or set()
    inferred = []
    for a in attrs:
        off = ndim - a.ndim
        inferred.append(DistAttr(merged[off:],
                                 sorted(a.partial_dims & common)))
    return inferred, DistAttr(merged, sorted(common))


@register_spmd_rule("reduction")
def reduction_rule(x: DistAttr, axis=None, keep_dim=False, linear=True):
    """Parity: `spmd_rules/reduction.cc`.  Reducing over a sharded dim
    leaves the output partial on that mesh dim (for linear reductions)."""
    ndim = x.ndim
    if axis is None:
        axes = list(range(ndim))
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        axes = [a % ndim for a in axes]
    out_mapping = []
    if linear:
        xi = x
        new_partial = set(x.partial_dims)
    else:
        # nonlinear reductions (max/min) over pending sums are wrong:
        # the inferred input demands p->r first
        xi = DistAttr(list(x.dims_mapping))
        new_partial = set()
    for i, dm in enumerate(x.dims_mapping):
        if i in axes:
            if dm != -1 and linear:
                new_partial.add(dm)
            if keep_dim:
                out_mapping.append(-1)
        else:
            out_mapping.append(dm)
    return [xi], DistAttr(out_mapping, sorted(new_partial))


@register_spmd_rule("reshape")
def reshape_rule(x: DistAttr, src_shape, dst_shape):
    """Parity: `spmd_rules/reshape.cc` (dim_trans.cc).  Walks matching
    size-product groups: 1-to-1 dims keep their shard; a split src dim
    gives its shard to the group's leading dst dim; merged src dims give
    the leading src dim's shard to the dst dim.  Anything irregular
    replicates."""
    out_mapping = [-1] * len(dst_shape)
    in_mapping = list(x.dims_mapping)
    si = di = 0
    while si < len(src_shape) and di < len(dst_shape):
        s_prod, d_prod = src_shape[si], dst_shape[di]
        s_end, d_end = si + 1, di + 1
        while s_prod != d_prod:
            if s_prod < d_prod and s_end < len(src_shape):
                s_prod *= src_shape[s_end]
                s_end += 1
            elif d_prod < s_prod and d_end < len(dst_shape):
                d_prod *= dst_shape[d_end]
                d_end += 1
            else:
                # irregular: demand a fully replicated input
                return [DistAttr([-1] * x.ndim, sorted(x.partial_dims))], \
                    DistAttr(out_mapping, sorted(x.partial_dims))
        # group [si:s_end] -> [di:d_end]: leading dim carries the shard;
        # sharded NON-leading dims of a merged group cannot survive a local
        # reshape — the inferred input replicates them (forces a reshard)
        out_mapping[di] = x.dims_mapping[si]
        for j in range(si + 1, s_end):
            in_mapping[j] = -1
        si, di = s_end, d_end
    return [DistAttr(in_mapping, sorted(x.partial_dims))], \
        DistAttr(out_mapping, sorted(x.partial_dims))


@register_spmd_rule("transpose")
def transpose_rule(x: DistAttr, perm):
    """Parity: `spmd_rules/transpose.cc`."""
    return [x], DistAttr([x.dims_mapping[p] for p in perm],
                         sorted(x.partial_dims))


@register_spmd_rule("embedding")
def embedding_rule(ids: DistAttr, w: DistAttr):
    """Parity: `spmd_rules/embedding.cc` — vocab-sharded weight makes the
    output partial over that mesh dim (each shard contributes the rows it
    owns); sharded embedding dim flows through."""
    row_dm, col_dm = w.dims_mapping
    out_mapping = list(ids.dims_mapping) + [col_dm]
    partial = set(ids.partial_dims)
    if row_dm != -1:
        partial.add(row_dm)
    return [ids, w], DistAttr(out_mapping, sorted(partial))


@register_spmd_rule("softmax")
def softmax_rule(x: DistAttr, axis=-1):
    """Parity: `spmd_rules/softmax.cc` — the normalized axis must be
    unsharded, and (nonlinear op) any pending partial sum must be resolved
    BEFORE the op: the inferred input clears partial, demanding a p->r
    reshard from the caller."""
    axis = axis % x.ndim
    mapping = list(x.dims_mapping)
    mapping[axis] = -1
    inferred = DistAttr(mapping)  # partial must be resolved first
    return [inferred], DistAttr(list(mapping))


@register_spmd_rule("layer_norm")
def layer_norm_rule(x: DistAttr, scale: DistAttr, bias: DistAttr,
                    begin_norm_axis=-1):
    """Parity: `spmd_rules/layer_norm.cc` — normalized trailing dims are
    unsharded; scale/bias replicated."""
    axis = begin_norm_axis % x.ndim
    mapping = list(x.dims_mapping)
    for i in range(axis, x.ndim):
        mapping[i] = -1
    # nonlinear in x: pending partials must resolve before the op
    xi = DistAttr(mapping)
    rep = DistAttr([-1] * scale.ndim)
    return [xi, rep, DistAttr([-1] * bias.ndim)], DistAttr(list(mapping))


@register_spmd_rule("cross_entropy_with_softmax")
def cross_entropy_rule(logits: DistAttr, label: DistAttr, axis=-1):
    """Parity: `spmd_rules/cross_entropy_with_softmax.cc` — class-dim
    sharding stays (parallel cross entropy) and makes the loss partial."""
    axis = axis % logits.ndim
    cls_dm = logits.dims_mapping[axis]
    batch_dms = [dm for i, dm in enumerate(logits.dims_mapping)
                 if i != axis]
    # merge the batch axes with the label's leading dims (a hard label may
    # carry a trailing size-1 dim: [B, 1] vs logits [B, C])
    n_b = len(batch_dms)
    lab_dms = list(label.dims_mapping)
    merged = [_merge_dim(b, l) for b, l in
              zip(batch_dms, lab_dms[:n_b] + [-1] * max(n_b - label.ndim,
                                                        0))]
    if cls_dm != -1 and cls_dm in merged:
        cls_dm = -1  # class mesh dim already used by a batch axis
    logits_mapping = list(merged)
    logits_mapping.insert(axis, cls_dm)
    li = DistAttr(logits_mapping)
    lab_mapping = merged[:min(label.ndim, n_b)] + \
        [-1] * max(label.ndim - n_b, 0)
    lab = DistAttr(lab_mapping)
    partial = {cls_dm} if cls_dm != -1 else set()
    return [li, lab], DistAttr(merged, sorted(partial))


@register_spmd_rule("concat")
def concat_rule(attrs: List[DistAttr], axis=0):
    """Parity: `spmd_rules/concat.cc` — concat axis unsharded, others
    merged."""
    ndim = attrs[0].ndim
    axis = axis % ndim
    merged = [-1] * ndim
    for a in attrs:
        for i, dm in enumerate(a.dims_mapping):
            if i != axis:
                merged[i] = _merge_dim(merged[i], dm)
    merged[axis] = -1
    # concat is linear, but a dim may stay partial only if ALL inputs are
    # partial over it (else the later reduce corrupts the resolved parts)
    common = None
    for a in attrs:
        common = set(a.partial_dims) if common is None \
            else common & a.partial_dims
    common = common or set()
    inferred = [DistAttr(list(merged), sorted(a.partial_dims & common))
                for a in attrs]
    return inferred, DistAttr(merged, sorted(common))


@register_spmd_rule("split")
def split_rule(x: DistAttr, num, axis=0):
    """Parity: `spmd_rules/split.cc`."""
    axis = axis % x.ndim
    mapping = list(x.dims_mapping)
    mapping[axis] = -1
    xi = DistAttr(mapping, sorted(x.partial_dims))
    return [xi], [DistAttr(list(mapping), sorted(x.partial_dims))
                  for _ in range(num)]


@register_spmd_rule("flash_attention")
def flash_attention_rule(q: DistAttr, k: DistAttr, v: DistAttr,
                         causal=True):
    """Parity: `spmd_rules/flash_attention.cc`.  Paddle flash-attn layout
    is [B, S, H, D] (`nn/functional/attention.py`): batch (0) and heads
    (2) merge and stay sharded; sequence (1) and head_dim (3) must be
    unsharded (ring attention handles sequence sharding separately)."""
    b = _merge_dim(_merge_dim(q.dims_mapping[0], k.dims_mapping[0]),
                   v.dims_mapping[0])
    h = _merge_dim(_merge_dim(q.dims_mapping[2], k.dims_mapping[2]),
                   v.dims_mapping[2])
    if h == b and b != -1:
        h = -1  # one mesh axis cannot back two tensor dims
    attr = DistAttr([b, -1, h, -1])
    return [attr, attr, attr], DistAttr([b, -1, h, -1])
