"""Scrape endpoint: a stdlib HTTP daemon serving /metrics, /healthz and
/requests.

ISSUE 6 tentpole (c): the answer to "what is p99 TTFT right now?" from
OUTSIDE the process.  One ``http.server.ThreadingHTTPServer`` on a
daemon thread — no third-party dependency, nothing on the hot path (the
handler reads the registry under its locks exactly like ``snapshot()``).

Endpoints:

* ``GET /metrics``  — the registry in Prometheus text exposition format
  (:func:`.export.render_prometheus`), content type
  ``text/plain; version=0.0.4``.
* ``GET /healthz``  — liveness JSON (``{"ok": true, ...}``); a scraper
  or load balancer can distinguish "process up" from "port dead".
* ``GET /requests`` — the last-K per-request serving trace records as a
  JSON array (``?n=`` caps K, default 64).

Security: binds ``FLAGS_metrics_host`` (default ``127.0.0.1`` — the
endpoint exposes operational data, so exposure beyond the host must be
an explicit operator decision).  ``FLAGS_metrics_port`` (default 0 =
disabled) gates auto-start: :func:`start_from_flags` is called by
``ServingEngine.run()`` and ``Model.fit()`` and is a no-op unless the
flag is set.  Calling :func:`serve` directly with ``port=0`` binds an
ephemeral port (tests).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import export as _export
from . import metrics as _metrics

__all__ = ["MetricsServer", "serve", "start_from_flags", "stop", "current"]


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_metrics/1.0"

    def _send(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            if url.path == "/metrics":
                body = _export.render_prometheus().encode()
                self._send(200,
                           "text/plain; version=0.0.4; charset=utf-8",
                           body)
            elif url.path == "/healthz":
                import os
                doc = {"ok": True, "pid": os.getpid(),
                       "unix_time": round(time.time(), 3),
                       "metrics_enabled": _metrics.enabled()}
                self._send(200, "application/json",
                           json.dumps(doc).encode())
            elif url.path == "/requests":
                try:
                    n = int(parse_qs(url.query).get("n", ["64"])[0])
                except (ValueError, IndexError):
                    n = 64
                body = json.dumps(_export.recent_requests(n),
                                  default=repr).encode()
                self._send(200, "application/json", body)
            else:
                self._send(404, "text/plain; charset=utf-8",
                           b"not found; endpoints: /metrics /healthz "
                           b"/requests\n")
        except BrokenPipeError:  # scraper hung up mid-response
            pass

    def log_message(self, format, *args):  # noqa: A002 - http.server API
        pass  # scrapes every few seconds must not spam stderr


class MetricsServer:
    """One running scrape endpoint; ``port`` is the BOUND port (useful
    when constructed with port 0)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="paddle-tpu-metrics",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_lock = threading.Lock()
_server: Optional[MetricsServer] = None


def serve(port: int, host: str = "127.0.0.1") -> MetricsServer:
    """Start (or return) the process's scrape endpoint.  Idempotent: a
    second call returns the running server regardless of arguments."""
    global _server
    with _lock:
        if _server is None:
            _server = MetricsServer(port, host)
        return _server


def start_from_flags() -> Optional[MetricsServer]:
    """Auto-start hook for the long-running entry points
    (``ServingEngine.run``, ``Model.fit``): starts the endpoint when
    ``FLAGS_metrics_port`` > 0, else a no-op.  Never raises — a busy
    port must not take down training/serving."""
    if _server is not None:
        return _server
    try:
        from .. import flags as _flags
        port = int(_flags.get_flag("metrics_port"))
        if port <= 0:
            return None
        host = str(_flags.get_flag("metrics_host"))
        return serve(port, host)
    except Exception:  # noqa: BLE001 - observability must not kill the job
        return None


def current() -> Optional[MetricsServer]:
    return _server


def stop() -> None:
    global _server
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
