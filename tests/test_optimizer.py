"""Optimizer + LR scheduler tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _train(opt_cls, steps=60, **kw):
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = opt_cls(parameters=net.parameters(), **kw)
    X = paddle.to_tensor(np.random.RandomState(0).rand(32, 4).astype("float32"))
    Y = X.sum(axis=1, keepdim=True)
    loss = None
    for _ in range(steps):
        loss = nn.MSELoss()(net(X), Y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss.item())


@pytest.mark.parametrize("cls,kw", [
    (optimizer.SGD, dict(learning_rate=0.1)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (optimizer.Adam, dict(learning_rate=0.05)),
    (optimizer.AdamW, dict(learning_rate=0.05, weight_decay=0.01)),
    (optimizer.RMSProp, dict(learning_rate=0.01)),
    (optimizer.Adagrad, dict(learning_rate=0.3)),
    (optimizer.Adamax, dict(learning_rate=0.1)),
    # lr=0.1 sits on a chaotic knife-edge for Lamb's trust ratio on this
    # tiny net: 1-ulp forward differences (op fusion order) flip whether it
    # lands under the threshold; 0.05 converges robustly
    (optimizer.Lamb, dict(learning_rate=0.05)),
])
def test_optimizers_converge(cls, kw):
    assert _train(cls, **kw) < 0.2


def test_sgd_matches_manual():
    p = paddle.Parameter(np.array([1.0, 2.0], np.float32))
    p.grad = paddle.to_tensor([0.5, 0.5])
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.95, 1.95], rtol=1e-6)


def test_adam_bias_correction_first_step():
    p = paddle.Parameter(np.array([1.0], np.float32))
    p.grad = paddle.to_tensor([0.1])
    opt = optimizer.Adam(learning_rate=0.001, parameters=[p])
    opt.step()
    # first step of Adam moves by ~lr regardless of grad magnitude
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.001], rtol=1e-3)


def test_adamw_decoupled_decay():
    p = paddle.Parameter(np.array([10.0], np.float32))
    p.grad = paddle.to_tensor([0.0])
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[p])
    opt.step()
    # pure decay: w -= lr * wd * w
    np.testing.assert_allclose(p.numpy(), [10.0 - 0.1 * 0.5 * 10.0], rtol=1e-5)


def test_param_groups():
    a = paddle.Parameter(np.ones(2, np.float32))
    b = paddle.Parameter(np.ones(2, np.float32))
    a.grad = paddle.to_tensor([1.0, 1.0])
    b.grad = paddle.to_tensor([1.0, 1.0])
    opt = optimizer.SGD(learning_rate=0.1, parameters=[
        {"params": [a]},
        {"params": [b], "learning_rate": 0.1},  # 0.1 * base lr
    ])
    opt.step()
    np.testing.assert_allclose(a.numpy(), [0.9, 0.9], rtol=1e-6)
    np.testing.assert_allclose(b.numpy(), [0.99, 0.99], rtol=1e-5)


def test_multi_precision_master_weights():
    p = paddle.Parameter(np.ones(4, np.float32))
    p._value = p._value.astype("bfloat16")
    p.grad = paddle.to_tensor(np.full(4, 1e-3, np.float32))
    opt = optimizer.SGD(learning_rate=0.01, parameters=[p],
                        multi_precision=True)
    for _ in range(10):
        p.grad = paddle.to_tensor(np.full(4, 1e-3, np.float32))
        opt.step()
    # master accumulates small updates that bf16 alone would lose
    mw = opt._accumulators["master_weight"][id(p)]
    np.testing.assert_allclose(np.asarray(mw), np.full(4, 1 - 1e-4), rtol=1e-4)


def test_optimizer_state_dict_roundtrip():
    p = paddle.Parameter(np.ones(2, np.float32))
    p.grad = paddle.to_tensor([1.0, 1.0])
    opt = optimizer.Adam(learning_rate=0.01, parameters=[p])
    opt.step()
    sd = opt.state_dict()
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=[p])
    opt2.set_state_dict(sd)
    assert opt2._global_step == 1
    np.testing.assert_allclose(
        np.asarray(opt2._accumulators["moment1"][id(p)]),
        np.asarray(opt._accumulators["moment1"][id(p)]))


def test_lr_scheduler_with_optimizer():
    sched = optimizer.lr.MultiStepDecay(0.1, milestones=[2, 4], gamma=0.1)
    p = paddle.Parameter(np.ones(1, np.float32))
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    lrs = []
    for _ in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.01, 0.01, 0.001], rtol=1e-6)


@pytest.mark.parametrize("sched_fn,expected0", [
    (lambda: optimizer.lr.ExponentialDecay(1.0, 0.5), 1.0),
    (lambda: optimizer.lr.StepDecay(1.0, 2, 0.5), 1.0),
    (lambda: optimizer.lr.CosineAnnealingDecay(1.0, 10), 1.0),
    (lambda: optimizer.lr.PolynomialDecay(1.0, 10), 1.0),
    (lambda: optimizer.lr.LinearWarmup(1.0, 5, 0.0, 1.0), 0.0),
    (lambda: optimizer.lr.NoamDecay(64, 100), None),
    (lambda: optimizer.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001]), 0.1),
    (lambda: optimizer.lr.InverseTimeDecay(1.0, 0.5), 1.0),
    (lambda: optimizer.lr.LambdaDecay(1.0, lambda e: 0.9 ** e), 1.0),
    (lambda: optimizer.lr.OneCycleLR(1.0, 10), None),
    (lambda: optimizer.lr.CyclicLR(0.1, 1.0, 5), None),
])
def test_schedulers_run(sched_fn, expected0):
    s = sched_fn()
    if expected0 is not None:
        assert abs(s() - expected0) < 1e-6
    for _ in range(12):
        s.step()
    assert np.isfinite(s())


def test_reduce_on_plateau():
    s = optimizer.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    for v in [1.0, 1.0, 1.0, 1.0]:
        s.step(v)
    assert s() == 0.5


def test_cosine_decay_reaches_min():
    s = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10, eta_min=0.1)
    for _ in range(10):
        s.step()
    np.testing.assert_allclose(s(), 0.1, atol=1e-6)


def test_grad_clip_in_optimizer():
    p = paddle.Parameter(np.zeros(2, np.float32))
    p.grad = paddle.to_tensor([30.0, 40.0])  # norm 50
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                        grad_clip=nn.ClipGradByGlobalNorm(5.0))
    opt.step()
    np.testing.assert_allclose(p.numpy(), [-3.0, -4.0], rtol=1e-5)


# ================================================== fused-vs-per-param parity
#
# The round-7 tentpole: Optimizer.step routed through ONE donated jitted
# program over the whole pytree (FLAGS_fused_optimizer) must agree with
# the per-leaf path to exact bits for fp32 (allclose <= 1e-6 for mixed
# precision with master weights), including clipping, the GradScaler
# skip step, and a state_dict round trip across a fused<->per-param
# switch mid-training.

from paddle_tpu.flags import flag_guard  # noqa: E402
from paddle_tpu import amp  # noqa: E402

_SHAPES = [(7,), (3, 5), (2, 3, 4), (11,), (1,)]


def _make_params(dtype="float32", seed=0):
    rng = np.random.RandomState(seed)
    params = []
    for shape in _SHAPES:
        p = paddle.Parameter(rng.uniform(-1, 1, shape).astype(np.float32))
        if dtype != "float32":
            p._value = p._value.astype(dtype)
        params.append(p)
    return params


def _grads_for_step(step, seed=0, inf_at=None):
    rng = np.random.RandomState(seed * 1000 + step)
    grads = [rng.uniform(-1, 1, s).astype(np.float32) for s in _SHAPES]
    if inf_at is not None and step == inf_at:
        grads[2] = grads[2].copy()
        grads[2].flat[0] = np.inf
    return grads


def _run_training(opt_cls, kw, fused, steps=4, dtype="float32",
                  multi_precision=False, clip=None, scaler_kw=None,
                  inf_at=None, switch_at=None):
    """Run `steps` deterministic optimizer steps; returns a dict of
    final param / master / accumulator arrays (as fp32 numpy) plus the
    scaler scale.  `switch_at`: step index at which FLAGS_fused_optimizer
    flips (for the mid-training switch test)."""
    with flag_guard(fused_optimizer=fused):
        params = _make_params(dtype=dtype)
        opt = opt_cls(parameters=params, multi_precision=multi_precision,
                      grad_clip=clip() if clip else None, **kw)
        scaler = amp.GradScaler(**scaler_kw) if scaler_kw else None
        for s in range(steps):
            if switch_at is not None and s == switch_at:
                paddle.set_flags({"fused_optimizer": not fused})
            for p, g in zip(params, _grads_for_step(s, inf_at=inf_at)):
                scale = scaler._scale if scaler else 1.0
                p.grad = paddle.to_tensor(g * scale)
            if scaler is not None:
                scaler.step(opt)
            else:
                opt.step()
            opt.clear_grad()
        out = {"params": [np.asarray(p._value, np.float32) for p in params]}
        for name, store in opt._accumulators.items():
            out[name] = [np.asarray(store[id(p)], np.float32)
                         for p in params if id(p) in store]
        if scaler is not None:
            out["scale"] = scaler._scale
            out["found_inf"] = scaler._found_inf
        return out


def _assert_runs_match(a, b, exact=True):
    assert set(a) == set(b)
    for key in a:
        if key in ("scale", "found_inf"):
            assert a[key] == b[key], f"{key}: {a[key]} != {b[key]}"
            continue
        for i, (x, y) in enumerate(zip(a[key], b[key])):
            if exact:
                np.testing.assert_array_equal(
                    x, y, err_msg=f"{key}[{i}] diverged")
            else:
                np.testing.assert_allclose(
                    x, y, atol=1e-6, rtol=0, err_msg=f"{key}[{i}]")


@pytest.mark.parametrize("cls,kw", [
    (optimizer.SGD, dict(learning_rate=0.1)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (optimizer.Adam, dict(learning_rate=0.05)),
    (optimizer.AdamW, dict(learning_rate=0.05, weight_decay=0.01)),
])
@pytest.mark.parametrize("clip", [None, lambda: nn.ClipGradByGlobalNorm(1.0)])
def test_fused_matches_per_param_fp32_exact(cls, kw, clip):
    ref = _run_training(cls, kw, fused=False, clip=clip)
    fus = _run_training(cls, kw, fused=True, clip=clip)
    _assert_runs_match(ref, fus, exact=True)


@pytest.mark.parametrize("cls,kw", [
    (optimizer.SGD, dict(learning_rate=0.1)),
    (optimizer.Adam, dict(learning_rate=0.05)),
    (optimizer.AdamW, dict(learning_rate=0.05, weight_decay=0.01)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
])
def test_fused_matches_per_param_bf16_master(cls, kw):
    ref = _run_training(cls, kw, fused=False, dtype="bfloat16",
                        multi_precision=True)
    fus = _run_training(cls, kw, fused=True, dtype="bfloat16",
                        multi_precision=True)
    _assert_runs_match(ref, fus, exact=False)


@pytest.mark.parametrize("clip", [None, lambda: nn.ClipGradByGlobalNorm(1.0)])
def test_fused_matches_per_param_scaler_skip_step(clip):
    """An inf grad at step 1 must skip the update and halve the scale on
    both paths; later steps use the decreased scale identically."""
    kw = dict(learning_rate=0.05)
    sk = dict(init_loss_scaling=16.0, incr_every_n_steps=3)
    ref = _run_training(optimizer.Adam, kw, fused=False, clip=clip,
                        scaler_kw=sk, inf_at=1)
    fus = _run_training(optimizer.Adam, kw, fused=True, clip=clip,
                        scaler_kw=sk, inf_at=1)
    assert ref["scale"] == 8.0
    _assert_runs_match(ref, fus, exact=True)


def test_fused_clip_by_norm_and_value_parity():
    for clip in (lambda: nn.ClipGradByNorm(0.7),
                 lambda: nn.ClipGradByValue(0.3)):
        ref = _run_training(optimizer.Momentum,
                            dict(learning_rate=0.1, momentum=0.9),
                            fused=False, clip=clip)
        fus = _run_training(optimizer.Momentum,
                            dict(learning_rate=0.1, momentum=0.9),
                            fused=True, clip=clip)
        _assert_runs_match(ref, fus, exact=True)


def test_fused_need_clip_false_subset_stays_fused():
    with flag_guard(fused_optimizer=True):
        from paddle_tpu.observability import metrics as obs
        params = _make_params()
        params[1].need_clip = False
        opt = optimizer.SGD(learning_rate=0.5, parameters=params,
                            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        before = obs.get("optimizer.fused").value(kind="fallback")
        for p, g in zip(params, _grads_for_step(0)):
            p.grad = paddle.to_tensor(g)
        opt.step()
        assert obs.get("optimizer.fused").value(kind="fallback") == before
    # parity against the per-leaf path with the same static mask
    def run(fused):
        with flag_guard(fused_optimizer=fused):
            ps = _make_params()
            ps[1].need_clip = False
            o = optimizer.SGD(learning_rate=0.5, parameters=ps,
                              grad_clip=nn.ClipGradByGlobalNorm(1.0))
            for p, g in zip(ps, _grads_for_step(0)):
                p.grad = paddle.to_tensor(g)
            o.step()
            return [np.asarray(p._value) for p in ps]
    for x, y in zip(run(False), run(True)):
        np.testing.assert_array_equal(x, y)


def test_fused_l1_decay_falls_back():
    from paddle_tpu.observability import metrics as obs
    from paddle_tpu.regularizer import L1Decay
    with flag_guard(fused_optimizer=True):
        params = _make_params()
        opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=params,
                                 weight_decay=L1Decay(0.01))
        before = obs.get("optimizer.fused").value(kind="fallback")
        for p, g in zip(params, _grads_for_step(0)):
            p.grad = paddle.to_tensor(g)
        opt.step()
        # L1's sign-term rides the per-leaf path; counted as a fallback
        assert obs.get("optimizer.fused").value(kind="fallback") == \
            before + 1


def test_fused_param_groups_and_lr_scale_parity():
    def run(fused):
        with flag_guard(fused_optimizer=fused):
            a = paddle.Parameter(np.ones(4, np.float32))
            b = paddle.Parameter(np.ones(4, np.float32))
            b.optimize_attr["learning_rate"] = 0.5
            opt = optimizer.SGD(learning_rate=0.1, parameters=[
                {"params": [a]},
                {"params": [b], "learning_rate": 0.1},
            ])
            for s in range(3):
                a.grad = paddle.to_tensor(np.full(4, 1.0 + s, np.float32))
                b.grad = paddle.to_tensor(np.full(4, 2.0 + s, np.float32))
                opt.step()
                opt.clear_grad()
            return np.asarray(a._value), np.asarray(b._value)
    ra, rb = run(False)
    fa, fb = run(True)
    np.testing.assert_array_equal(ra, fa)
    np.testing.assert_array_equal(rb, fb)


def test_fused_state_dict_roundtrip_across_switch():
    """state_dict written by a fused run restores into a per-param run
    (and vice versa): 3 fused steps + reload + 3 per-param steps must
    equal 6 uninterrupted per-param steps."""
    ref = _run_training(optimizer.Adam, dict(learning_rate=0.05),
                        fused=False, steps=6)

    with flag_guard(fused_optimizer=True):
        params = _make_params()
        opt = optimizer.Adam(learning_rate=0.05, parameters=params)
        for s in range(3):
            for p, g in zip(params, _grads_for_step(s)):
                p.grad = paddle.to_tensor(g)
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
    with flag_guard(fused_optimizer=False):
        opt2 = optimizer.Adam(learning_rate=0.05, parameters=params)
        opt2.set_state_dict(sd)
        assert opt2._global_step == 3
        for s in range(3, 6):
            for p, g in zip(params, _grads_for_step(s)):
                p.grad = paddle.to_tensor(g)
            opt2.step()
            opt2.clear_grad()
    for x, y in zip(ref["params"],
                    [np.asarray(p._value) for p in params]):
        np.testing.assert_array_equal(x, y)


def test_fused_switch_mid_training_is_seamless():
    ref = _run_training(optimizer.AdamW, dict(learning_rate=0.05),
                        fused=False, steps=6)
    mixed = _run_training(optimizer.AdamW, dict(learning_rate=0.05),
                          fused=True, steps=6, switch_at=3)
    _assert_runs_match(ref, mixed, exact=True)


def test_fused_step_dispatch_count():
    """Acceptance: a 50-leaf Adam step with global-norm clip + scaler
    executes as <= 3 optimizer-layer XLA dispatches when fused (vs >= 50
    per-leaf), measured on the shared dispatch.ops instrument."""
    from paddle_tpu.observability import metrics as obs

    _OPT_OPS = ("optimizer.fused_step", "optimizer.leaf_update",
                "clip.tree", "amp.unscale")

    def opt_dispatches():
        c = obs.get("dispatch.ops")
        return sum(c.value(op=k) for k in _OPT_OPS) if c else 0

    def one_run(fused):
        with flag_guard(fused_optimizer=fused, enable_metrics=True):
            rng = np.random.RandomState(0)
            params = [paddle.Parameter(rng.rand(17).astype(np.float32))
                      for _ in range(50)]
            opt = optimizer.Adam(learning_rate=1e-3, parameters=params,
                                 grad_clip=nn.ClipGradByGlobalNorm(1.0))
            scaler = amp.GradScaler(init_loss_scaling=8.0)

            def step():
                for p in params:
                    p.grad = paddle.to_tensor(
                        rng.rand(17).astype(np.float32))
                scaler.step(opt)
            step()  # warm/compile
            before = opt_dispatches()
            step()
            return opt_dispatches() - before

    assert one_run(fused=True) <= 3
    assert one_run(fused=False) >= 50


def test_fused_host_side_global_norm_hook_falls_back():
    """A cross-mesh reduce hook that forces host concretization cannot
    trace into the fused program — the step must FALL BACK (not crash)
    and agree with the per-leaf path, which splits its clip around the
    eager hook call."""
    def run(fused):
        with flag_guard(fused_optimizer=fused):
            params = _make_params()
            clip = nn.ClipGradByGlobalNorm(1.0)
            clip._global_norm_reduce_fn = lambda sq: float(sq) * 2.0
            opt = optimizer.SGD(learning_rate=0.5, parameters=params,
                                grad_clip=clip)
            for p, g in zip(params, _grads_for_step(0)):
                p.grad = paddle.to_tensor(g)
            opt.step()
            return [np.asarray(p._value) for p in params]
    for x, y in zip(run(False), run(True)):
        np.testing.assert_array_equal(x, y)


def test_fused_hit_miss_counter():
    from paddle_tpu.observability import metrics as obs
    with flag_guard(fused_optimizer=True, enable_metrics=True):
        params = _make_params()
        opt = optimizer.Adam(learning_rate=0.01, parameters=params)
        c = obs.get("optimizer.fused")
        miss0, hit0 = c.value(kind="miss"), c.value(kind="hit")
        for s in range(3):
            for p, g in zip(params, _grads_for_step(s)):
                p.grad = paddle.to_tensor(g)
            opt.step()
            opt.clear_grad()
        # one trace for the tree, then cache hits
        assert c.value(kind="miss") == miss0 + 1
        assert c.value(kind="hit") == hit0 + 2
