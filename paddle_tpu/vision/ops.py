"""paddle.vision.ops — populated from the YAML single source
(namespace: vision_ops).  Parity: python/paddle/vision/ops.py."""


# ---- ops from the YAML single source ----
from paddle_tpu.ops.generated_ops import export_namespace as _exp  # noqa: E402
_exp(globals(), "vision_ops")
del _exp

# ---- hand ops (optional-tensor inputs the generated wrappers can't
# express: mask is a traced input only in the v2 form) ----
import functools as _functools

from paddle_tpu.ops import codegen_helpers as _h
from paddle_tpu.ops.registry import dispatch as _d, register_op as _reg

_reg("deformable_conv",
     lambda x, offset, weight, mask, *, stride, padding, dilation,
     deformable_groups, groups: _h.deformable_conv(
         x, offset, weight, mask, stride=stride, padding=padding,
         dilation=dilation, deformable_groups=deformable_groups,
         groups=groups))
_reg("deformable_conv_v1",
     lambda x, offset, weight, *, stride, padding, dilation,
     deformable_groups, groups: _h.deformable_conv(
         x, offset, weight, None, stride=stride, padding=padding,
         dilation=dilation, deformable_groups=deformable_groups,
         groups=groups))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1 (mask=None) / v2.  Parity:
    python/paddle/vision/ops.py:883 deform_conv2d (deformable_conv op):
    bilinear-sampled im2col + one MXU matmul (see
    ops/codegen_helpers.py deformable_conv)."""
    statics = {"stride": stride, "padding": padding, "dilation": dilation,
               "deformable_groups": deformable_groups, "groups": groups}
    if mask is None:
        out = _d("deformable_conv_v1", (x, offset, weight), statics)
    else:
        out = _d("deformable_conv", (x, offset, weight, mask), statics)
    if bias is not None:
        from paddle_tpu.ops import manipulation as _m
        out = out + _m.reshape(bias, [1, -1, 1, 1])
    return out


deformable_conv = deform_conv2d


# ---- eager detection ops (dynamic output sizes: NMS-style selection;
# the reference returns LoD tensors here.  Deliberately eager-only — a
# compiled serving graph uses fixed-topk variants instead) ----

import numpy as _np

from paddle_tpu.framework.tensor import Tensor as _T


def _np_of(x):
    return _np.asarray(x._value if isinstance(x, _T) else x)


def _iou_matrix(a, b):
    """[Na, 4] x [Nb, 4] (x1, y1, x2, y2) -> [Na, Nb] IoU."""
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    x1 = _np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = _np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = _np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = _np.minimum(a[:, None, 3], b[None, :, 3])
    inter = _np.clip(x2 - x1, 0, None) * _np.clip(y2 - y1, 0, None)
    return inter / _np.maximum(area_a[:, None] + area_b[None] - inter,
                               1e-10)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (FPN paper eq.1).  Parity:
    python/paddle/vision/ops.py distribute_fpn_proposals /
    distribute_fpn_proposals op.  Returns (multi_rois [per level],
    restore_index, rois_num_per_level or None)."""
    rois = _np_of(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = _np.sqrt(_np.clip((rois[:, 2] - rois[:, 0] + off) *
                              (rois[:, 3] - rois[:, 1] + off), 1e-8, None))
    lvl = _np.floor(_np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = _np.clip(lvl, min_level, max_level).astype(_np.int64)
    import jax.numpy as jnp
    multi, order, nums = [], [], []
    for lv in range(min_level, max_level + 1):
        idx = _np.nonzero(lvl == lv)[0]
        multi.append(_T._wrap(jnp.asarray(rois[idx])))
        order.append(idx)
        nums.append(len(idx))
    order = _np.concatenate(order) if order else _np.zeros((0,), _np.int64)
    restore = _np.empty_like(order)
    restore[order] = _np.arange(len(order))
    restore_t = _T._wrap(jnp.asarray(restore.reshape(-1, 1)))
    nums_t = [_T._wrap(jnp.asarray(_np.array([n], _np.int32)))
              for n in nums] if rois_num is not None else None
    return multi, restore_t, nums_t


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (optionally per-category).  Parity:
    python/paddle/vision/ops.py nms."""
    b = _np_of(boxes)
    s = _np.arange(len(b))[::-1].astype(_np.float64) \
        if scores is None else _np_of(scores).astype(_np.float64)
    cats = None if category_idxs is None else _np_of(category_idxs)

    def nms_single(idxs):
        idxs = idxs[_np.argsort(-s[idxs], kind="stable")]
        keep = []
        while len(idxs):
            i = idxs[0]
            keep.append(i)
            if len(idxs) == 1:
                break
            ious = _iou_matrix(b[i:i + 1], b[idxs[1:]])[0]
            idxs = idxs[1:][ious <= iou_threshold]
        return _np.asarray(keep, _np.int64)

    if cats is None:
        keep = nms_single(_np.arange(len(b)))
    else:
        parts = [nms_single(_np.nonzero(cats == c)[0])
                 for c in (categories if categories is not None
                           else _np.unique(cats))]
        keep = _np.concatenate([p for p in parts if len(p)]) \
            if parts else _np.zeros((0,), _np.int64)
        keep = keep[_np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    import jax.numpy as jnp
    return _T._wrap(jnp.asarray(keep))


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): parallel soft suppression by pairwise IoU.
    Parity: python/paddle/vision/ops.py matrix_nms / matrix_nms op.
    bboxes [N, M, 4]; scores [N, C, M].  Returns (out [R, 6], optional
    index, rois_num)."""
    bb = _np_of(bboxes)
    sc = _np_of(scores)
    N, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for n in range(N):
        rows = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            sel = _np.nonzero(s > score_threshold)[0]
            if not len(sel):
                continue
            sel = sel[_np.argsort(-s[sel], kind="stable")][:nms_top_k]
            boxes_c = bb[n, sel]
            s_c = s[sel]
            iou = _np.triu(_iou_matrix(boxes_c, boxes_c), 1)
            # matrix-NMS decay (SOLOv2 eq.4): per pair (i, j) the decay is
            # f(iou_ij)/f(compensate_i), compensate_i = max overlap that
            # box i itself suffered from any higher-scored box; take the
            # min over i < j
            k = len(sel)
            compensate = iou.max(axis=0) if k > 1 else _np.zeros(k)
            comp_m = _np.broadcast_to(compensate[:, None], (k, k))
            if use_gaussian:
                ratio = _np.exp(-(iou ** 2 - comp_m ** 2) / gaussian_sigma)
            else:
                ratio = (1 - iou) / _np.maximum(1 - comp_m, 1e-10)
            # pairs with i >= j don't suppress: neutral ratio 1
            ratio = _np.where(_np.triu(_np.ones((k, k), bool), 1),
                              ratio, 1.0)
            decay = ratio.min(axis=0)
            dec_s = s_c * decay
            ok = dec_s >= post_threshold
            for j in _np.nonzero(ok)[0]:
                rows.append((c, dec_s[j], *boxes_c[j], sel[j] + n * M))
        rows.sort(key=lambda r: -r[1])
        rows = rows[:keep_top_k] if keep_top_k > 0 else rows
        nums.append(len(rows))
        for r in rows:
            outs.append(r[:6])
            idxs.append(r[6])
    import jax.numpy as jnp
    out = _T._wrap(jnp.asarray(_np.asarray(outs, _np.float32).reshape(
        -1, 6)))
    res = [out]
    if return_index:
        res.append(_T._wrap(jnp.asarray(
            _np.asarray(idxs, _np.int64).reshape(-1, 1))))
    if return_rois_num:
        res.append(_T._wrap(jnp.asarray(_np.asarray(nums, _np.int32))))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation: decode anchors, clip, filter small, NMS.
    Parity: python/paddle/vision/ops.py generate_proposals /
    generate_proposals_v2 op."""
    sc = _np_of(scores)          # [N, A, H, W]
    bd = _np_of(bbox_deltas)     # [N, 4A, H, W]
    im = _np_of(img_size)        # [N, 2] (h, w)
    an = _np_of(anchors).reshape(-1, 4)
    var = _np_of(variances).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    rois_all, num_all, scores_all = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # H*W*A
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        # anchors/variances come as [H, W, A, 4] (or already flat in the
        # same H-major order the score flatten above produces)
        aa, vv = an, var
        order = _np.argsort(-s, kind="stable")[:pre_nms_top_n]
        s, d, aa, vv = s[order], d[order], aa[order], vv[order]
        # decode (cxcywh deltas on anchor boxes)
        aw = aa[:, 2] - aa[:, 0] + off
        ah = aa[:, 3] - aa[:, 1] + off
        acx = aa[:, 0] + aw * 0.5
        acy = aa[:, 1] + ah * 0.5
        cx = vv[:, 0] * d[:, 0] * aw + acx
        cy = vv[:, 1] * d[:, 1] * ah + acy
        w = _np.exp(_np.clip(vv[:, 2] * d[:, 2], None, 10)) * aw
        h = _np.exp(_np.clip(vv[:, 3] * d[:, 3], None, 10)) * ah
        boxes = _np.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2 - off, cy + h / 2 - off], axis=1)
        ih, iw = im[n, 0], im[n, 1]
        boxes[:, 0::2] = _np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = _np.clip(boxes[:, 1::2], 0, ih - off)
        ok = ((boxes[:, 2] - boxes[:, 0] + off >= min_size) &
              (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[ok], s[ok]
        keep = []
        idxs = _np.arange(len(boxes))
        while len(idxs) and len(keep) < post_nms_top_n:
            i = idxs[0]
            keep.append(i)
            if len(idxs) == 1:
                break
            ious = _iou_matrix(boxes[i:i + 1], boxes[idxs[1:]])[0]
            idxs = idxs[1:][ious <= nms_thresh]
        rois_all.append(boxes[keep])
        scores_all.append(s[keep])
        num_all.append(len(keep))
    import jax.numpy as jnp
    rois = _T._wrap(jnp.asarray(_np.concatenate(rois_all, axis=0)
                                .astype(_np.float32)))
    rscores = _T._wrap(jnp.asarray(_np.concatenate(scores_all, axis=0)
                                   .astype(_np.float32).reshape(-1, 1)))
    if return_rois_num:
        return rois, rscores, _T._wrap(jnp.asarray(
            _np.asarray(num_all, _np.int32)))
    return rois, rscores


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8.  Parity:
    python/paddle/vision/ops.py decode_jpeg (decode_jpeg op; the
    reference decodes via nvjpeg on GPU — here PIL on host, an IO-path
    op that has no place on the TPU)."""
    import io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg needs PIL in this build") from e
    data = _np_of(x).astype(_np.uint8).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode in ("gray", "grey", "L"):
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    import jax.numpy as jnp
    return _T._wrap(jnp.asarray(arr))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=1000,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   return_rois_num=True, rois_num=None, name=None):
    """Per-class hard NMS + cross-class keep_top_k.  Parity:
    python/paddle/vision/ops.py multiclass_nms (multiclass_nms3 op).
    bboxes [N, M, 4]; scores [N, C, M].  Returns (out [R, 6],
    rois_num, optional index)."""
    bb = _np_of(bboxes)
    sc = _np_of(scores)
    N, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for n in range(N):
        rows = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            sel = _np.nonzero(s > score_threshold)[0]
            if not len(sel):
                continue
            sel = sel[_np.argsort(-s[sel], kind="stable")][:nms_top_k]
            keep = []
            cand = sel
            while len(cand):
                i = cand[0]
                keep.append(i)
                if len(cand) == 1:
                    break
                ious = _iou_matrix(bb[n, i:i + 1], bb[n, cand[1:]])[0]
                cand = cand[1:][ious <= nms_threshold]
            for i in keep:
                rows.append((c, s[i], *bb[n, i], i + n * M))
        rows.sort(key=lambda r: -r[1])
        rows = rows[:keep_top_k] if keep_top_k > 0 else rows
        nums.append(len(rows))
        for r in rows:
            outs.append(r[:6])
            idxs.append(r[6])
    import jax.numpy as jnp
    out = _T._wrap(jnp.asarray(
        _np.asarray(outs, _np.float32).reshape(-1, 6)))
    res = [out]
    if return_rois_num:
        res.append(_T._wrap(jnp.asarray(_np.asarray(nums, _np.int32))))
    if return_index:
        res.append(_T._wrap(jnp.asarray(
            _np.asarray(idxs, _np.int64).reshape(-1, 1))))
    return tuple(res) if len(res) > 1 else out
