"""Crash-only serving (ISSUE 15): failure isolation, poison-request
quarantine, the tick watchdog, graceful drain, and warm restart from an
exported prefix cache — every recovery path driven by deterministic
chaos injection.

The headline contracts pinned here:

* a request whose admission program raises (or whose prefill logits go
  non-finite under the NaN watchdog) strikes out after two attempts and
  is rejected ``reason=poisoned`` — the engine loop survives and the
  block ledger stays balanced;
* a transient dispatch failure under ``FLAGS_serving_dispatch_retries``
  is INVISIBLE: the retried stream is bit-identical to an uninjected
  run;
* per-slot non-finite decode logits evict exactly the implicated slot
  ``outcome=error`` while every other slot's greedy stream stays
  BIT-identical to an uninjected run (blocksan armed and clean);
* a harvest that never materializes trips the tick watchdog
  (``FLAGS_serving_tick_timeout_s``) and fails the tick instead of
  wedging the loop;
* drain closes admission (healthz 503 ``draining``), cancels the
  waiting queue ``outcome=drained``, and exports the prefix cache
  through the atomic-manifest machinery; a fresh engine imports it and
  a cached-prefix prompt's stream bit-matches the warm engine's
  prefix-hit path — while corrupt export versions are skipped with a
  counter, never loaded.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability import flight_recorder
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.testing import chaos


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_context", 64)
    kw.setdefault("block_size", 16)
    return ServingEngine(model, **kw)


def _counter(name, **labels):
    snap = obs_metrics.snapshot().get(name)
    if not snap:
        return 0
    for s in snap["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return 0


# ------------------------------------------------------- poison quarantine

def test_poison_quarantine_after_two_dispatch_strikes(model):
    """A request whose prefill program raises is re-queued once, then
    quarantined ``reason=poisoned`` — the loop survives, every block is
    released, and the evidence lands on counters + the flight ring.
    (The injection fires BEFORE the program call, so this test compiles
    nothing.)"""
    eng = _engine(model)
    bad = eng.add_request(Request([5, 6, 7], max_new_tokens=4))
    p0 = _counter("serving.poisoned_requests")
    with chaos.fail_at("serving.prefill.dispatch", on_calls=[1, 2],
                       exc=RuntimeError) as fault:
        eng.run()
    assert fault.fires == 2
    assert bad._strikes == 2
    assert bad.outcome == "poisoned"
    assert bad.trace["outcome"] == "rejected:poisoned"
    assert bad in eng.finished and not bad.output_ids
    assert eng.poisoned_requests == 1 and eng.tick_errors == 2
    assert _counter("serving.poisoned_requests") == p0 + 1
    assert _counter("serving.rejections", reason="poisoned") >= 1
    # nothing leaked: the failed admissions undid every draw
    st = eng.stats()
    assert st["free_blocks"] == eng.num_blocks and st["reserved"] == 0
    events = [e for e in flight_recorder.default_recorder().events()
              if e["kind"] == "poison_quarantine"]
    assert events and events[-1]["rid"] == bad.rid


def test_transient_dispatch_retry_is_invisible(model):
    """One injected transient RuntimeError under
    ``FLAGS_serving_dispatch_retries`` retries in place: the stream is
    BIT-identical to an uninjected run, the request finishes, and the
    retry is counted — no strike, no eviction."""
    ref = _engine(model)
    rr = ref.add_request(Request([5, 6, 7], max_new_tokens=4))
    ref.run()
    eng = _engine(model)
    req = eng.add_request(Request([5, 6, 7], max_new_tokens=4))
    with flag_guard(serving_dispatch_retries=2):
        with chaos.fail_at("serving.prefill.dispatch", on_calls=[1],
                           exc=RuntimeError) as fault:
            eng.run()
    assert fault.fires == 1
    assert req.outcome == "finished"
    assert req.output_ids == rr.output_ids
    assert eng.dispatch_retries == 1 and eng.tick_errors == 0
    assert _counter("serving.dispatch_retries",
                    site="serving.prefill.dispatch") >= 1


@pytest.mark.slow   # two engines compile their grids (~4-8s)
def test_nan_prefill_quarantine_and_batch_isolation(model):
    """NaN-injected prefill logits (flight-recorder watchdog armed)
    strike the poisoned request twice -> quarantined, while a healthy
    request admitted through the SAME engine streams bit-identically to
    an uninjected run.  The NaN is screened BEFORE prefix registration,
    so the shared index never holds a poisoned prompt."""
    ref = _engine(model)
    rr = ref.add_request(Request([5, 6, 7], max_new_tokens=4))
    ref.run()
    eng = _engine(model, prefix_cache=True)
    bad = eng.add_request(Request([9, 9, 9], max_new_tokens=4))
    ok = eng.add_request(Request([5, 6, 7], max_new_tokens=4))
    with flag_guard(enable_nan_watchdog=True):
        with chaos.nan_logits("serving.prefill", rids=[bad.rid]) as f:
            eng.run()
    assert f.fires == 2
    assert bad.outcome == "poisoned" and not bad.output_ids
    assert ok.outcome == "finished"
    assert ok.output_ids == rr.output_ids
    # the poisoned prompt must not be in the prefix index
    assert eng.prefix.lookup(bad.prompt_ids).blocks == []
    st = eng.stats()
    assert st["free_blocks"] == eng.num_blocks and st["reserved"] == 0


@pytest.mark.slow   # two engines + two runs compile (~4-8s)
def test_decode_nan_evicts_only_implicated_slot_bit_parity(model):
    """ACCEPTANCE (ISSUE 15): chaos-injected non-finite logits on slot
    i — the per-slot failure the host-sampling decode path can
    attribute — end that request ``outcome=error`` with its blocks
    fully released (blocksan armed: the verify at every boundary and at
    the drained end stays green), and every OTHER slot's greedy stream
    is BIT-identical to an uninjected run."""
    def serve(inject=None):
        with flag_guard(serving_device_sampling=False,
                        enable_nan_watchdog=True, enable_jaxsan=True):
            eng = ServingEngine(model, max_batch=3, max_context=64,
                                block_size=16, steps_per_tick=1)
            reqs = [eng.add_request(Request([5 + i, 6, 7],
                                            max_new_tokens=6))
                    for i in range(3)]
            if inject is not None:
                with chaos.nan_logits("serving.decode",
                                      rids=[reqs[inject].rid]):
                    eng.run()
            else:
                eng.run()
            return eng, reqs

    _, ref = serve()
    eng, reqs = serve(inject=1)
    assert reqs[1].outcome == "error"
    assert len(reqs[1].output_ids) == 1      # the prefill token only
    for i in (0, 2):
        assert reqs[i].outcome == "finished"
        assert reqs[i].output_ids == ref[i].output_ids
    st = eng.stats()
    assert st["free_blocks"] == eng.num_blocks and st["reserved"] == 0
    assert eng._blocksan is not None and eng._blocksan.verifies > 0
    evs = [e for e in flight_recorder.default_recorder().events()
           if e["kind"] == "slot_error"]
    assert evs and evs[-1]["rid"] == reqs[1].rid


@pytest.mark.slow   # compiles one engine grid (~5s) — the fast twin is
                    # the prefill-stage quarantine test above
def test_tick_dispatch_failure_evicts_batch_ledger_clean(model):
    """A TICK-level dispatch failure (the whole-batch program raised —
    no slot attributable) evicts exactly the slots the tick covered,
    outcome=error, with blocksan armed: the eviction's block releases
    reconcile at the drained end (the R007 error-path audit's runtime
    regression evidence)."""
    with flag_guard(enable_jaxsan=True):
        eng = _engine(model)
        reqs = [eng.add_request(Request([5 + i, 6, 7],
                                        max_new_tokens=6))
                for i in range(2)]
        # admission prefills fire a DIFFERENT site, so the tick
        # site's first call is the first mid-stream decode tick
        with chaos.fail_at("serving.tick.dispatch", on_calls=[1],
                           exc=RuntimeError) as f:
            eng.run()
    assert f.fires == 1
    assert eng.tick_errors == 1
    for r in reqs:
        assert r.outcome == "error"
        assert len(r.output_ids) >= 1     # the prefill token landed
    st = eng.stats()
    assert st["free_blocks"] == eng.num_blocks and st["reserved"] == 0
    assert eng._blocksan is not None and eng._blocksan.verifies > 0


# ------------------------------------------------------------ tick watchdog

@pytest.mark.slow  # 7s measured (PR 18 re-budget): compiles an engine grid around a stalled harvest; the drain/admission + retry pins stay fast
def test_tick_watchdog_fails_hung_harvest(model):
    """A harvest stalled past ``FLAGS_serving_tick_timeout_s`` raises
    TickTimeout inside the loop; the guard absorbs it — implicated
    slots evicted ``outcome=error``, blocks released — and run()
    returns instead of wedging forever."""
    eng = _engine(model)
    req = eng.add_request(Request([5, 6, 7], max_new_tokens=6))
    t0 = _counter("serving.tick_errors")
    with flag_guard(serving_tick_timeout_s=0.3):
        with chaos.delay_at("serving.harvest", 3.0, on_calls=[1]) as f:
            eng.run()
    assert f.fires == 1
    assert req.outcome == "error"
    assert eng.tick_errors == 1
    assert _counter("serving.tick_errors") == t0 + 1
    st = eng.stats()
    assert st["free_blocks"] == eng.num_blocks and st["reserved"] == 0
    # watchdog off (default): the same delay merely slows the harvest
    eng2 = _engine(model)
    r2 = eng2.add_request(Request([5, 6, 7], max_new_tokens=2))
    with chaos.delay_at("serving.harvest", 0.05):
        eng2.run()
    assert r2.outcome == "finished"


# ------------------------------------------------------------------- drain

def test_drain_cancels_waiting_closes_admission_and_healthz(model):
    """drain() with no admitted work: the waiting queue is cancelled
    ``outcome=drained``, admission rejects (reason=draining), and
    health() reports the draining state with in-flight/waiting counts.
    (No request ever admits, so this test compiles nothing.)"""
    eng = _engine(model)
    eng.run()                       # no work: marks ready, zero ticks
    assert eng.health()["ready"] is True
    waiting = [eng.add_request(Request([5, 6, 7], max_new_tokens=4))
               for _ in range(2)]
    eng.request_drain()
    doc = eng.health()
    assert doc == {"ready": False, "reason": "draining", "in_flight": 0,
                   "waiting": 2, "prefilling": 0}
    with pytest.raises(ValueError, match="draining"):
        eng.add_request(Request([1, 2], max_new_tokens=2))
    assert _counter("serving.rejections", reason="draining") >= 1
    info = eng.drain(deadline_s=5.0)
    assert info["cancelled_waiting"] == 2
    assert info["evicted_running"] == 0 and info["export"] is None
    for r in waiting:
        assert r.outcome == "drained" and r in eng.finished
        assert r.trace["outcome"] == "drained"
    assert eng.drain() is info      # idempotent
    st = eng.stats()
    assert st["draining"] is True and st["drain"]["cancelled_waiting"] == 2
    assert st["free_blocks"] == eng.num_blocks


@pytest.mark.slow   # compiles the engine grid and ticks through a
                    # stream mid-drain (~2-6s)
def test_drain_finishes_in_flight_and_verifies_blocksan(model):
    """An ADMITTED request finishes inside the drain deadline (its
    stream completes normally); blocksan is armed, so the drain-end
    verify reconciling the emptied ledger is a hard assertion, not a
    no-op."""
    with flag_guard(enable_jaxsan=True):
        eng = _engine(model)
        req = eng.add_request(Request([5, 6, 7], max_new_tokens=4))
        eng.step()                  # admit + first tick
        assert req.slot is not None and not req.done
        info = eng.drain(deadline_s=30.0)
        assert req.outcome == "finished"
        assert len(req.output_ids) == 4
        assert info["evicted_running"] == 0
        assert eng._blocksan is not None and eng._blocksan.verifies > 0
        assert eng.stats()["free_blocks"] == eng.num_blocks


@pytest.mark.slow   # compiles one engine then drains past the deadline
def test_drain_deadline_evicts_stragglers(model):
    """A request that cannot finish inside the deadline is evicted
    ``outcome=drained`` with its blocks released."""
    eng = _engine(model)
    req = eng.add_request(Request([5, 6, 7], max_new_tokens=30))
    eng.step()
    info = eng.drain(deadline_s=0.0)
    assert req.outcome == "drained" and not req.done
    assert info["evicted_running"] == 1
    assert eng.stats()["free_blocks"] == eng.num_blocks


# ------------------------------------------- export / import warm restart

SYS_PROMPT = list(range(1, 40))


def _serve_one(eng, suffix, n=6):
    r = eng.add_request(Request(SYS_PROMPT + suffix, max_new_tokens=n))
    eng.run()
    return r


@pytest.mark.slow   # two prefix engines compile their grids (~14s)
def test_drain_export_then_import_bit_matches_prefix_hit_path(model):
    """ACCEPTANCE (ISSUE 15): drain -> export -> new engine import: the
    token stream for a cached-prefix prompt BIT-matches the warm
    engine's prefix-hit path, and the import re-pinned the blocks
    through the ordinary accounting (blocksan armed on the importing
    engine, free-block invariant intact)."""
    tmp = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                       f"pfx_export_{os.getpid()}")
    with flag_guard(serving_prefix_export_dir=tmp):
        a = _engine(model, max_context=96, prefix_cache=True)
        _serve_one(a, [77])                  # registers the prefix
        hit = _serve_one(a, [88])            # the warm prefix-HIT path
        assert a.stats()["prefix_cache"]["hits"] == 1
        info = a.drain()
        exp = info["export"]
        assert exp["entries"] == exp["blocks"] == 2
        assert os.path.exists(os.path.join(exp["path"], "COMPLETE"))
        i0 = _counter("serving.prefix_import_blocks")
        with flag_guard(enable_jaxsan=True):
            b = _engine(model, max_context=96, prefix_cache=True)
        imp = b.stats()["prefix_cache"]["import"]
        assert imp == {"step": 1, "blocks": 2, "skipped_corrupt": 0}
        assert _counter("serving.prefix_import_blocks") == i0 + 2
        rb = _serve_one(b, [88])
        assert rb.output_ids == hit.output_ids
        assert b.stats()["prefix_cache"]["hits"] == 1
        assert b.stats()["free_blocks"] == b.num_blocks


def test_corrupt_export_skipped_with_counter_and_fallback(model):
    """Corrupted/truncated export versions are SKIPPED — counter +
    flight event, never loaded — and import falls back to the next
    older valid version.  (Exports are hand-built through the same
    commit helper, so nothing here compiles.)"""
    from paddle_tpu.distributed.checkpoint import manager as ckpt
    tmp = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                       f"pfx_corrupt_{os.getpid()}")
    probe = ServingEngine(model, max_batch=2, max_context=64,
                          block_size=16, prefix_cache=True)
    meta = probe._prefix_fingerprint()
    nh, bs, hd = probe.nh, probe.bs, probe.hd
    layers = probe.model.cfg.num_layers
    dtype = np.asarray(probe.pools[0][0]).dtype

    def fabricate(step, n_entries):
        index = {"schema": "paddle_tpu.prefix/v1", "block_size": bs,
                 "meta": meta,
                 "entries": [{"hash": f"{i:02d}" * 16, "parent": None,
                              "block": i + 1}
                             for i in range(n_entries)]}
        arrays = {"block_ids": np.arange(1, n_entries + 1, dtype=np.int64)}
        for li in range(layers):
            arrays[f"k{li}"] = np.full((nh, n_entries, bs, hd), step,
                                       dtype)
            arrays[f"v{li}"] = np.full((nh, n_entries, bs, hd), -step,
                                       dtype)

        def write(d):
            with open(os.path.join(d, "prefix_index.json"), "w") as f:
                json.dump(index, f)
            with open(os.path.join(d, "prefix_blocks.npz"), "wb") as f:
                np.savez(f, **arrays)
            return ["prefix_index.json", "prefix_blocks.npz"]

        return ckpt.commit_single_rank(tmp, step, write)

    fabricate(1, n_entries=1)                   # older, valid
    newest = fabricate(2, n_entries=2)          # newest — then corrupted
    chaos.flip_bytes(os.path.join(newest, "prefix_blocks.npz"), 64, 8)
    s0 = _counter("serving.prefix_import_skipped_corrupt",
                  reason="corrupt")
    with flag_guard(serving_prefix_export_dir=tmp):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, prefix_cache=True)
    imp = eng.stats()["prefix_cache"]["import"]
    assert imp == {"step": 1, "blocks": 1, "skipped_corrupt": 1}
    assert _counter("serving.prefix_import_skipped_corrupt",
                    reason="corrupt") == s0 + 1
    evs = [e for e in flight_recorder.default_recorder().events()
           if e["kind"] == "prefix_import_skip"]
    assert evs and evs[-1]["step"] == 2
    # the imported block holds version 1's bytes (never version 2's)
    blk = eng.prefix.resident_blocks()[0]
    assert float(np.asarray(eng.pools[0][0])[:, blk].ravel()[0]) == 1.0
    # a fingerprint mismatch is also skipped, with its own reason
    import shutil
    with open(os.path.join(newest, "prefix_index.json")) as f:
        idx = json.load(f)
    shutil.rmtree(newest)
    idx["meta"] = dict(meta, quant="int8")
    m0 = _counter("serving.prefix_import_skipped_corrupt",
                  reason="mismatch")

    def write_mismatch(d):
        with open(os.path.join(d, "prefix_index.json"), "w") as f:
            json.dump(idx, f)
        with open(os.path.join(d, "prefix_blocks.npz"), "wb") as f:
            np.savez(f, block_ids=np.asarray([1], np.int64))
        return ["prefix_index.json", "prefix_blocks.npz"]

    ckpt.commit_single_rank(tmp, 3, write_mismatch)
    with flag_guard(serving_prefix_export_dir=tmp):
        eng2 = ServingEngine(model, max_batch=2, max_context=64,
                             block_size=16, prefix_cache=True)
    assert _counter("serving.prefix_import_skipped_corrupt",
                    reason="mismatch") == m0 + 1
    assert eng2.stats()["prefix_cache"]["import"]["step"] == 1


def test_export_state_import_state_round_trip():
    """PrefixCache.export_state orders entries parent-first and
    import_state rebuilds the index (child counters included) onto
    remapped blocks, skipping orphans when capacity cuts a parent."""
    from paddle_tpu.inference.prefix_cache import PrefixCache
    src = PrefixCache(4)
    refs = []
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]
    src.register(prompt, [7, 8, 9], refs.append)
    assert len(src) == 3 and len(refs) == 3
    state = src.export_state()
    # parent-first: depth increases monotonically
    assert [e["block"] for e in state["entries"]] == [7, 8, 9]
    assert state["entries"][0]["parent"] is None
    dst = PrefixCache(4)
    alloc_ids = iter([101, 102, 103])
    mapping = {}
    n = dst.import_state(state, lambda: next(alloc_ids),
                         lambda old, new: mapping.__setitem__(old, new))
    assert n == 3 and mapping == {7: 101, 8: 102, 9: 103}
    # the chain resolves lookups exactly as the source did
    assert dst.lookup(prompt).blocks == [101, 102, 103]
    assert dst.lookup(prompt[:8]).blocks == [101, 102]
    # capacity cut: only the root fits -> children skipped, no orphans
    dst2 = PrefixCache(4)
    short = iter([201])
    n2 = dst2.import_state(state,
                           lambda: next(short, None),
                           lambda old, new: None)
    assert n2 == 1 and len(dst2) == 1
    assert dst2.lookup(prompt).blocks == [201]
    # block_size mismatch refuses loudly
    with pytest.raises(ValueError, match="block_size"):
        PrefixCache(8).import_state(state, lambda: 1, lambda a, b: None)
