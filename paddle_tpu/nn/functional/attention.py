"""Attention functionals.

`scaled_dot_product_attention` is the public API (parity:
`paddle.nn.functional.scaled_dot_product_attention` and the PHI
flash-attention path `phi/kernels/gpu/flash_attn_kernel.cu`).  On TPU the
fast path is a Pallas flash-attention kernel (paddle_tpu/ops/pallas_kernels.py,
used when running on TPU with supported shapes); the fallback is a fused XLA
softmax(QK^T)V which XLA already schedules well on the MXU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops.registry import dispatch as _d, register_op

__all__ = ["scaled_dot_product_attention", "flash_attention", "sdpa_xla"]


def _sdpa_xla_impl(q, k, v, mask, *, causal, dropout_p, scale, key):
    # inputs [B, S, H, D] (paddle flash_attn layout); compute in [B,H,S,D]
    if k.shape[2] != q.shape[2]:  # GQA fallback: repeat kv heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = qh.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        idx_q = jnp.arange(q_len)[:, None]
        idx_k = jnp.arange(k_len)[None, :]
        cmask = idx_q >= (idx_k - (k_len - q_len))
        logits = jnp.where(cmask, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


register_op("sdpa", _sdpa_xla_impl, tags=("mxu", "fused"))


def sdpa_xla(query, key, value, attn_mask=None, dropout_p=0.0,
             is_causal=False, scale=None, training=True):
    from ...framework import random as _random
    rng = _random.next_key() if (dropout_p > 0 and training) else None
    return _d("sdpa", (query, key, value, attn_mask),
              {"causal": bool(is_causal),
               "dropout_p": float(dropout_p) if training else 0.0,
               "scale": scale, "key": rng})


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seq, heads, head_dim] like paddle's flash-attn API.

    Key-padding masks ([B, Sk] / [B, 1, 1, Sk] boolean keep-masks) and
    attention dropout ride the Pallas flash kernel on TPU; additive or
    full [Sq, Sk] masks take the XLA path."""
    from ...ops import pallas_kernels
    B, Sk = query.shape[0], key.shape[1]
    kv_mask = pallas_kernels.as_kv_padding_mask(attn_mask, B, Sk)
    residual_mask = attn_mask if kv_mask is None else None
    if pallas_kernels.flash_attention_available(query, key, value,
                                                residual_mask):
        return pallas_kernels.flash_attention(
            query, key, value, causal=is_causal,
            dropout_p=dropout_p if training else 0.0, kv_mask=kv_mask)
    if kv_mask is not None:
        # a recognized integer 0/1 padding mask must KEEP its keep-mask
        # semantics on the XLA path too (the non-bool sdpa branch would
        # ADD it to the logits — a silent no-op)
        attn_mask = (kv_mask != 0).reshape(B, 1, 1, Sk)
    return sdpa_xla(query, key, value, attn_mask, dropout_p, is_causal,
                    None, training)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None
