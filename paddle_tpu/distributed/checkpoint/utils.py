"""Checkpoint helpers: state-dict flattening and slice arithmetic.

Parity: `python/paddle/distributed/checkpoint/utils.py` (flatten_state_dict)
plus the piece-intersection math the reference keeps in
`load_state_dict.py` (ReadItem computation).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

FLAT_SEP = "."


def flatten_state_dict(state_dict: Dict) -> Tuple[Dict[str, Any],
                                                  Dict[str, Tuple[str, ...]]]:
    """Flatten arbitrarily nested dicts to {dotted_key: leaf}.

    Returns (flat, mapping) where mapping records the original key path for
    each flat key so load can restore nesting.
    """
    flat: Dict[str, Any] = {}
    mapping: Dict[str, Tuple[str, ...]] = {}

    def visit(prefix: Tuple[str, ...], node):
        if isinstance(node, dict):
            for k, v in node.items():
                visit(prefix + (str(k),), v)
        else:
            key = FLAT_SEP.join(prefix)
            if key in flat:
                raise ValueError(f"duplicate flat key {key!r} in state_dict")
            flat[key] = node
            mapping[key] = prefix
        return None

    visit((), state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, Tuple[str, ...]]) -> Dict:
    out: Dict = {}
    for key, val in flat.items():
        path = mapping.get(key, (key,))
        node = out
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = val
    return out


def offset_of(index: Tuple[slice, ...], shape: Tuple[int, ...]):
    """Global offset of an addressable-shard index (tuple of slices)."""
    return tuple((sl.start or 0) for sl in index)


def copy_intersection(dst: np.ndarray, dst_offset, src: np.ndarray,
                      src_offset) -> int:
    """Copy the overlap of two global-coordinate boxes; returns copied elems.

    dst occupies [dst_offset, dst_offset+dst.shape); src likewise.  The
    intersection (if any) is copied from src into dst in place.
    """
    if dst.ndim == 0:
        dst[...] = src
        return 1
    lo = [max(a, b) for a, b in zip(dst_offset, src_offset)]
    hi = [min(a + s, b + t) for a, s, b, t in
          zip(dst_offset, dst.shape, src_offset, src.shape)]
    if any(h <= l for l, h in zip(lo, hi)):
        return 0
    dst_sl = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, dst_offset))
    src_sl = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, src_offset))
    dst[dst_sl] = src[src_sl]
    return int(np.prod([h - l for l, h in zip(lo, hi)]))
