from . import autograd_engine, dygraph, random  # noqa: F401
from .dygraph import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, is_tensor, to_tensor  # noqa: F401
