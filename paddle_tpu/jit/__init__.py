from .api import StaticFunction, ignore_module, not_to_static, to_static  # noqa: F401
