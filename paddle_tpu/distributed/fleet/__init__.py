"""paddle_tpu.distributed.fleet — hybrid parallel training.
Parity: `python/paddle/distributed/fleet/`."""

from . import random as rng_utils  # noqa: F401  (fleet.meta_parallel RNG)
from .fleet import (DistributedStrategy, HybridParallelOptimizer,  # noqa: F401
                    barrier_worker, distributed_model, distributed_optimizer,
                    get_hybrid_communicate_group, init, is_first_worker,
                    worker_index, worker_num)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa: F401
                        RowParallelLinear, VocabParallelEmbedding)
from .pipeline_parallel import (PipelineParallel,  # noqa: F401
                                PipelineParallelWithInterleave)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .random import get_rng_state_tracker  # noqa: F401
from .recompute import recompute  # noqa: F401
from .sharding import (DygraphShardingOptimizer,  # noqa: F401
                       GroupShardedOptimizerStage2, group_sharded_parallel)
from .spmd_pipeline import pipeline_forward, stack_stage_params  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401

# submodule aliases matching the reference layout
from . import mp_layers as meta_parallel  # noqa: F401
