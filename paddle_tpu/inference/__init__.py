"""Inference deployment API.

Parity: `paddle/fluid/inference/api/analysis_predictor.h:100` +
`python/paddle/inference/__init__.py` (Config, create_predictor, Tensor
handles with copy_from_cpu/copy_to_cpu).

TPU-native: the "analysis + optimization passes" of the reference are XLA's
job; a Predictor wraps the `jit.save` StableHLO artifact, pre-compiles on
first run, and serves through input/output handles.
"""

from .predictor import Config, PredictHandle, Predictor, create_predictor
from .passes import convert_to_int8, convert_to_mixed_precision
from .serving import Request, ServingEngine

__all__ = ["Config", "Predictor", "PredictHandle", "create_predictor",
           "convert_to_mixed_precision", "convert_to_int8",
           "Request", "ServingEngine"]
