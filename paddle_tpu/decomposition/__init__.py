"""Composite-op decomposition registry.

Parity: `python/paddle/decomposition/decomp.py:177` (decompose) +
`paddle/fluid/primitive/composite/composite.h` (the rule corpus).

On TPU the compiler fuses primitives back together, so decomposition's
role here is (a) a reference implementation corpus for testing fused ops
and (b) an escape hatch when a fused kernel must be lowered to primitives
(e.g. custom-AD through a composite).  Each rule maps an op name to a
pure-primitive implementation over paddle Tensors.
"""

from __future__ import annotations

import math
from typing import Callable, Dict

__all__ = ["register_decomp", "get_decomp", "has_decomp", "decompose",
           "list_decomps"]

_DECOMPS: Dict[str, Callable] = {}


def register_decomp(name: str):
    def deco(fn):
        _DECOMPS[name] = fn
        return fn
    return deco


def has_decomp(name: str) -> bool:
    return name in _DECOMPS


def get_decomp(name: str) -> Callable:
    if name not in _DECOMPS:
        raise KeyError(f"no decomposition registered for {name!r}")
    return _DECOMPS[name]


def list_decomps():
    return sorted(_DECOMPS)


def decompose(name: str, *args, **kwargs):
    return get_decomp(name)(*args, **kwargs)


# ------------------------------------------------------------ rule corpus
@register_decomp("gelu")
def _gelu(x, approximate=False):
    import paddle_tpu as paddle
    if approximate:
        c = math.sqrt(2.0 / math.pi)
        return 0.5 * x * (1.0 + paddle.tanh(c * (x + 0.044715 * x * x * x)))
    return 0.5 * x * (1.0 + paddle.erf(x / math.sqrt(2.0)))


@register_decomp("softmax")
def _softmax(x, axis=-1):
    import paddle_tpu as paddle
    m = paddle.max(x, axis=axis, keepdim=True)
    e = paddle.exp(x - m)
    return e / paddle.sum(e, axis=axis, keepdim=True)


@register_decomp("log_softmax")
def _log_softmax(x, axis=-1):
    import paddle_tpu as paddle
    m = paddle.max(x, axis=axis, keepdim=True)
    shifted = x - m
    return shifted - paddle.log(
        paddle.sum(paddle.exp(shifted), axis=axis, keepdim=True))


@register_decomp("silu")
def _silu(x):
    import paddle_tpu as paddle
    return x / (1.0 + paddle.exp(-x))


@register_decomp("layer_norm")
def _layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    import paddle_tpu as paddle
    mean = paddle.mean(x, axis=-1, keepdim=True)
    var = paddle.mean((x - mean) ** 2, axis=-1, keepdim=True)
    out = (x - mean) * paddle.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@register_decomp("rms_norm")
def _rms_norm(x, weight=None, epsilon=1e-6):
    import paddle_tpu as paddle
    ms = paddle.mean(x * x, axis=-1, keepdim=True)
    out = x * paddle.rsqrt(ms + epsilon)
    return out * weight if weight is not None else out


@register_decomp("mean")
def _mean(x, axis=None, keepdim=False):
    import paddle_tpu as paddle
    import numpy as np
    n = float(np.prod(x.shape)) if axis is None else \
        float(np.prod([x.shape[a] for a in
                      ([axis] if isinstance(axis, int) else axis)]))
    return paddle.sum(x, axis=axis, keepdim=keepdim) / n


@register_decomp("sigmoid")
def _sigmoid(x):
    import paddle_tpu as paddle
    return 1.0 / (1.0 + paddle.exp(-x))


@register_decomp("swiglu")
def _swiglu(x, y):
    import paddle_tpu as paddle
    return (x / (1.0 + paddle.exp(-x))) * y


@register_decomp("dropout")
def _dropout(x, p=0.5, training=True):
    import paddle_tpu as paddle
    if not training or p == 0:
        return x
    mask = paddle.cast(paddle.rand(x.shape) >= p, x.dtype)
    return x * mask / (1.0 - p)


# --------------------------------------------------- dispatch integration
import contextlib as _contextlib


@_contextlib.contextmanager
def enabled(*names, include_all: bool = False):
    """Substitute the named composite ops (or every registered rule with
    include_all=True) with their primitive decompositions at the dispatch
    seam — the dynamic-dispatch form of the reference's program
    `decompose()` pass (`python/paddle/decomposition/decomp.py:177`).

    Uses: testing fused kernels against their primitive oracles,
    higher-order AD through composites whose fused vjp is first-order
    only, and compiler canonicalization experiments.

        with decomposition.enabled("gelu", "layer_norm"):
            y = model(x)          # those ops run as primitive chains
    """
    from ..ops import registry as _reg
    active = set(_DECOMPS) if include_all else set(names)
    unknown = active - set(_DECOMPS)
    if unknown:
        raise KeyError(f"no decomposition registered for {sorted(unknown)}")
    prev = _reg._decomp_active
    if prev:
        active = active | prev   # nested contexts UNION, never narrow
    _reg.set_decomp_active(active)
    try:
        yield
    finally:
        _reg.set_decomp_active(prev)


# ------------------------------------------------- extended rule corpus
# Parity: `paddle/fluid/primitive/composite/composite.h` — the composite
# corpus the reference lowers in its decompose pass.  Signatures match
# the registry statics of the corresponding fused ops.

@register_decomp("relu")
def _relu(x):
    import paddle_tpu as paddle
    return paddle.maximum(x, 0.0)


@register_decomp("relu6")
def _relu6(x):
    import paddle_tpu as paddle
    return paddle.clip(x, 0.0, 6.0)


@register_decomp("leaky_relu")
def _leaky_relu(x, negative_slope=0.01):
    import paddle_tpu as paddle
    return paddle.maximum(x, 0.0) + negative_slope * paddle.minimum(x, 0.0)


@register_decomp("elu")
def _elu(x, alpha=1.0):
    import paddle_tpu as paddle
    # where-form: min/max clamping would zero the negative branch when
    # alpha < 0 (jax.nn.elu semantics keep it positive there)
    neg = alpha * (paddle.exp(paddle.minimum(x, 0.0)) - 1.0)
    return paddle.where(x > 0, x, neg)


@register_decomp("celu")
def _celu(x, alpha=1.0):
    import paddle_tpu as paddle
    neg = alpha * (paddle.exp(paddle.minimum(x, 0.0) / alpha) - 1.0)
    return paddle.where(x > 0, x, neg)


@register_decomp("selu")
def _selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    import paddle_tpu as paddle
    return scale * (paddle.maximum(x, 0.0) + paddle.minimum(
        alpha * (paddle.exp(paddle.minimum(x, 0.0)) - 1.0), 0.0))


@register_decomp("hardsigmoid")
def _hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    import paddle_tpu as paddle
    return paddle.clip(slope * x + offset, 0.0, 1.0)


@register_decomp("hardswish")
def _hardswish(x):
    import paddle_tpu as paddle
    return x * paddle.clip(x + 3.0, 0.0, 6.0) / 6.0


@register_decomp("mish")
def _mish(x):
    import paddle_tpu as paddle
    # stable softplus: max(x, 0) + log1p(exp(-|x|))
    sp = paddle.maximum(x, 0.0) + paddle.log1p(paddle.exp(-paddle.abs(x)))
    return x * paddle.tanh(sp)


@register_decomp("softplus")
def _softplus(x, beta=1.0, threshold=20.0):
    import paddle_tpu as paddle
    soft = paddle.log(1.0 + paddle.exp(beta * x)) / beta
    return paddle.where(x * beta > threshold, x, soft)


@register_decomp("log_sigmoid")
def _log_sigmoid(x):
    import paddle_tpu as paddle
    # stable form: min(x, 0) - log1p(exp(-|x|)) (naive -log(1+exp(-x))
    # overflows to -inf below ~-88 in float32)
    return paddle.minimum(x, 0.0) - paddle.log1p(paddle.exp(-paddle.abs(x)))


@register_decomp("tanhshrink")
def _tanhshrink(x):
    import paddle_tpu as paddle
    return x - paddle.tanh(x)


@register_decomp("softshrink")
def _softshrink(x, threshold=0.5):
    import paddle_tpu as paddle
    return paddle.where(x > threshold, x - threshold,
                        paddle.where(x < -threshold, x + threshold,
                                     0.0 * x))


@register_decomp("hardshrink")
def _hardshrink(x, threshold=0.5):
    import paddle_tpu as paddle
    keep = paddle.cast(paddle.logical_or(x > threshold, x < -threshold),
                       str(x.dtype))
    return x * keep


@register_decomp("hardtanh")
def _hardtanh(x, min=-1.0, max=1.0):
    import paddle_tpu as paddle
    return paddle.clip(x, min, max)


@register_decomp("batch_norm_apply")
def _batch_norm(x, weight, bias, mean, variance, eps=1e-5,
                channel_axis=1):
    import paddle_tpu as paddle
    shape = [1] * len(x.shape)
    shape[channel_axis] = -1
    out = (x - paddle.reshape(mean, shape)) * paddle.rsqrt(
        paddle.reshape(variance, shape) + eps)
    if weight is not None:
        out = out * paddle.reshape(weight, shape)
    if bias is not None:
        out = out + paddle.reshape(bias, shape)
    return out


@register_decomp("instance_norm")
def _instance_norm(x, weight=None, bias=None, eps=1e-5):
    import paddle_tpu as paddle
    axes = list(range(2, len(x.shape)))
    mean = paddle.mean(x, axis=axes, keepdim=True)
    var = paddle.mean((x - mean) ** 2, axis=axes, keepdim=True)
    out = (x - mean) * paddle.rsqrt(var + eps)
    shape = [1, -1] + [1] * (len(x.shape) - 2)
    if weight is not None:
        out = out * paddle.reshape(weight, shape)
    if bias is not None:
        out = out + paddle.reshape(bias, shape)
    return out


@register_decomp("group_norm")
def _group_norm(x, weight=None, bias=None, groups=1, eps=1e-5,
                channel_last=False):
    import paddle_tpu as paddle
    if channel_last:
        perm = [0, len(x.shape) - 1] + list(range(1, len(x.shape) - 1))
        x = paddle.transpose(x, perm)
    n, c = x.shape[0], x.shape[1]
    rest = list(x.shape[2:])
    g = paddle.reshape(x, [n, groups, c // groups] + rest)
    axes = list(range(2, len(g.shape)))
    mean = paddle.mean(g, axis=axes, keepdim=True)
    var = paddle.mean((g - mean) ** 2, axis=axes, keepdim=True)
    out = paddle.reshape((g - mean) * paddle.rsqrt(var + eps),
                         [n, c] + rest)
    shape = [1, -1] + [1] * (len(x.shape) - 2)
    if weight is not None:
        out = out * paddle.reshape(weight, shape)
    if bias is not None:
        out = out + paddle.reshape(bias, shape)
    if channel_last:
        inv = [0] + list(range(2, len(x.shape))) + [1]
        out = paddle.transpose(out, inv)
    return out


@register_decomp("mse_loss")
def _mse_loss(input, label, reduction="mean"):
    import paddle_tpu as paddle
    d = (input - label) ** 2
    if reduction == "mean":
        return paddle.mean(d)
    if reduction == "sum":
        return paddle.sum(d)
    return d


@register_decomp("huber_loss")
def _huber_loss(x, y, delta=1.0, reduction="mean"):
    import paddle_tpu as paddle
    r = paddle.abs(x - y)
    quad = 0.5 * r * r
    lin = delta * (r - 0.5 * delta)
    out = paddle.where(r <= delta, quad, lin)
    if reduction == "mean":
        return paddle.mean(out)
    if reduction == "sum":
        return paddle.sum(out)
    return out


@register_decomp("squared_l2_norm")
def _squared_l2_norm(x):
    import paddle_tpu as paddle
    return paddle.reshape(paddle.sum(x * x), [1])


# NOTE: the fused softmax-CE seat is the "cross_entropy" registry op
# (nn/functional/loss.py:70); a rule under a name no op dispatches would
# silently substitute nothing, so none is registered here.


@register_decomp("logsumexp")
def _logsumexp(x, axis=None, keepdim=False):
    import paddle_tpu as paddle
    m = paddle.max(x, axis=axis, keepdim=True)
    out = paddle.log(paddle.sum(paddle.exp(x - m), axis=axis,
                                keepdim=True)) + m
    if not keepdim:
        out = paddle.squeeze(out, axis)
    return out


@register_decomp("stanh")
def _stanh(x, scale_a=0.67, scale_b=1.7159):
    import paddle_tpu as paddle
    return scale_b * paddle.tanh(scale_a * x)


@register_decomp("addmm")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    import paddle_tpu as paddle
    return beta * input + alpha * paddle.matmul(x, y)


@register_decomp("lerp")
def _lerp(x, y, weight):
    return x + weight * (y - x)


# -------------------------------------------- round-5 corpus widening
# Parity: the remainder of `paddle/fluid/primitive/composite/composite.h`
# (add_n/any/flatten/index_sample/p_norm/reciprocal/square/squeeze/stack/
# unsqueeze/...) plus the loss composites the reference decomposes for
# higher-order AD (`fluid/primitive/rule/vjp/details.h`).  Every rule name
# is a DISPATCHED registry op and the signature mirrors the registered
# implementation, so `decomposition.enabled(name)` substitutes cleanly.

def _reduce(out, reduction):
    import paddle_tpu as paddle
    if reduction == "mean":
        return paddle.mean(out)
    if reduction == "sum":
        return paddle.sum(out)
    return out


@register_decomp("add_n")
def _add_n(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_decomp("any")
def _any(x, axis=None, keepdim=False):
    import paddle_tpu as paddle
    nz = paddle.cast(x != 0, "int32")    # truthiness = nonzero, matching
    return paddle.cast(                  # jnp.any for negatives/floats
        paddle.max(nz, axis=axis, keepdim=keepdim) > 0, "bool")


@register_decomp("all")
def _all(x, axis=None, keepdim=False):
    import paddle_tpu as paddle
    nz = paddle.cast(x != 0, "int32")
    return paddle.cast(
        paddle.min(nz, axis=axis, keepdim=keepdim) > 0, "bool")


@register_decomp("clip")
def _clip(x, min=None, max=None):  # noqa: A002
    import paddle_tpu as paddle
    if min is not None:
        x = paddle.maximum(x, paddle.full_like(x, min))
    if max is not None:
        x = paddle.minimum(x, paddle.full_like(x, max))
    return x


@register_decomp("reciprocal")
def _reciprocal(x):
    return 1.0 / x


@register_decomp("square")
def _square(x):
    return x * x


@register_decomp("flatten")
def _flatten(v, shape):
    import paddle_tpu as paddle
    return paddle.reshape(v, shape)


@register_decomp("squeeze")
def _squeeze(v, axis=None):
    import paddle_tpu as paddle
    shape = list(v.shape)
    if axis is None:
        new = [s for s in shape if s != 1]
    else:
        axes = axis if isinstance(axis, (list, tuple)) else (axis,)
        axes = {a % len(shape) for a in axes}
        new = [s for i, s in enumerate(shape) if not (i in axes and s == 1)]
    return paddle.reshape(v, new)


@register_decomp("unsqueeze")
def _unsqueeze(v, axis):
    import paddle_tpu as paddle
    shape = list(v.shape)
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    # jnp.expand_dims semantics: every axis (incl. negatives) resolves
    # against the FINAL output rank
    final = len(shape) + len(axes)
    for a in sorted(a % final for a in axes):
        shape.insert(a, 1)
    return paddle.reshape(v, shape)


@register_decomp("stack")
def _stack(vs, axis=0):
    import paddle_tpu as paddle
    return paddle.concat([decompose("unsqueeze", v, axis=axis)
                          for v in vs], axis=axis)


@register_decomp("index_sample")
def _index_sample(x, index):
    import paddle_tpu as paddle
    return paddle.take_along_axis(x, index, axis=1)


@register_decomp("p_norm")
def _p_norm(x, p=2, axis=None, keepdim=False):
    import paddle_tpu as paddle
    if p == "nuc":
        # nuclear norm = sum of singular values (mirrors the fused
        # kernel's SVD branch)
        _, s, _ = paddle.linalg.svd(x)
        return paddle.sum(s, axis=-1)
    if axis is None:
        ndim = len(x.shape)
        out = _p_norm(paddle.reshape(x, [-1]), p=p, axis=0, keepdim=False)
        if keepdim:   # fused kernel keeps EVERY reduced dim as 1
            out = paddle.reshape(out, [1] * ndim)
        return out
    if p == "fro" or p == 2:
        return paddle.sqrt(paddle.sum(x * x, axis=axis, keepdim=keepdim))
    if p == 1:
        return paddle.sum(paddle.abs(x), axis=axis, keepdim=keepdim)
    if p == float("inf"):
        return paddle.max(paddle.abs(x), axis=axis, keepdim=keepdim)
    if p == float("-inf"):
        return paddle.min(paddle.abs(x), axis=axis, keepdim=keepdim)
    if p == 0:
        return paddle.sum(paddle.cast(x != 0, x.dtype), axis=axis,
                          keepdim=keepdim)
    return paddle.pow(paddle.sum(paddle.pow(paddle.abs(x), p), axis=axis,
                                 keepdim=keepdim), 1.0 / p)


@register_decomp("dist")
def _dist(a, b, p=2):
    return decompose("p_norm", a - b, p=p, axis=None, keepdim=False)


@register_decomp("softsign")
def _softsign(x):
    import paddle_tpu as paddle
    return x / (1.0 + paddle.abs(x))


@register_decomp("thresholded_relu")
def _thresholded_relu(x, threshold=1.0):
    import paddle_tpu as paddle
    return paddle.where(x > threshold, x, paddle.zeros_like(x))


@register_decomp("glu")
def _glu(x, axis=-1):
    import paddle_tpu as paddle
    a, b = paddle.split(x, 2, axis=axis)
    return a * decompose("sigmoid", b)


@register_decomp("cosine_similarity")
def _cosine_similarity(x1, x2, axis=1, eps=1e-8):
    import paddle_tpu as paddle
    dot = paddle.sum(x1 * x2, axis=axis)
    n1 = paddle.sqrt(paddle.sum(x1 * x1, axis=axis))
    n2 = paddle.sqrt(paddle.sum(x2 * x2, axis=axis))
    return dot / paddle.maximum(n1 * n2, paddle.full_like(n1, eps))


@register_decomp("label_smooth")
def _label_smooth(label, epsilon=0.1):
    return label * (1.0 - epsilon) + epsilon / label.shape[-1]


# ----- loss composites (signatures mirror nn/functional/loss.py) -----

@register_decomp("mse_loss")
def _mse_loss(x, y, reduction="mean"):
    return _reduce((x - y) * (x - y), reduction)


@register_decomp("l1_loss")
def _l1_loss(x, y, reduction="mean"):
    import paddle_tpu as paddle
    return _reduce(paddle.abs(x - y), reduction)


@register_decomp("smooth_l1_loss")
def _smooth_l1_loss(x, y, reduction="mean", delta=1.0):
    import paddle_tpu as paddle
    d = paddle.abs(x - y)
    per = paddle.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce(per, reduction)


@register_decomp("kl_div")
def _kl_div(x, y, reduction="mean", log_target=False):
    import paddle_tpu as paddle
    if log_target:
        per = paddle.exp(y) * (y - x)
    else:
        per = y * (paddle.log(paddle.maximum(
            y, paddle.full_like(y, 1e-12))) - x)
    return _reduce(per, reduction)


@register_decomp("log_loss")
def _log_loss(pred, label, epsilon=1e-4):
    import paddle_tpu as paddle
    return (-label * paddle.log(pred + epsilon)
            - (1.0 - label) * paddle.log(1.0 - pred + epsilon))


@register_decomp("margin_ranking_loss")
def _margin_ranking_loss(x1, x2, y, margin=0.0, reduction="mean"):
    import paddle_tpu as paddle
    per = paddle.maximum(-y * (x1 - x2) + margin,
                         paddle.zeros_like(x1))
    return _reduce(per, reduction)


@register_decomp("hinge_embedding_loss")
def _hinge_embedding_loss(x, y, margin=1.0, reduction="mean"):
    import paddle_tpu as paddle
    neg = paddle.maximum(margin - x, paddle.zeros_like(x))
    per = paddle.where(y == 1, x, neg)
    return _reduce(per, reduction)


@register_decomp("cosine_embedding_loss")
def _cosine_embedding_loss(x1, x2, y, margin=0.0, reduction="mean"):
    import paddle_tpu as paddle
    cos = decompose("cosine_similarity", x1, x2, axis=-1, eps=1e-12)
    per = paddle.where(y == 1, 1.0 - cos,
                       paddle.maximum(cos - margin,
                                      paddle.zeros_like(cos)))
    return _reduce(per, reduction)


@register_decomp("triplet_margin_loss")
def _triplet_margin_loss(a, p, n, margin=1.0, pnorm=2, reduction="mean"):
    import paddle_tpu as paddle
    dp = decompose("p_norm", a - p, p=pnorm, axis=-1, keepdim=False)
    dn = decompose("p_norm", a - n, p=pnorm, axis=-1, keepdim=False)
    per = paddle.maximum(dp - dn + margin, paddle.zeros_like(dp))
    return _reduce(per, reduction)


@register_decomp("nll_loss")
def _nll_loss(logp, label, weight=None, ignore_index=-100,
              reduction="mean"):
    import paddle_tpu as paddle
    valid = label != ignore_index
    safe = paddle.cast(paddle.where(valid, label,
                                    paddle.zeros_like(label)), "int32")
    per = -paddle.take_along_axis(
        logp, decompose("unsqueeze", safe, axis=1), axis=1)
    per = decompose("squeeze", per, axis=1)
    if weight is not None:
        w = paddle.gather(weight, paddle.reshape(safe, [-1]))
        w = paddle.reshape(w, safe.shape)
    else:
        w = None
    per = paddle.where(valid, per * (w if w is not None else 1.0),
                       paddle.zeros_like(per))
    if reduction == "mean":
        if w is not None:
            denom = paddle.sum(paddle.where(
                valid, w, paddle.zeros_like(w)))
        else:
            denom = paddle.maximum(
                paddle.sum(paddle.cast(valid, per.dtype)),
                paddle.full_like(paddle.sum(per), 1.0))
        return paddle.sum(per) / denom
    return _reduce(per, reduction)
