from .auto_cast import (amp_guard, auto_cast, decorate,  # noqa: F401
                        FP16_WHITE_LIST, FP16_BLACK_LIST)
from .grad_scaler import GradScaler  # noqa: F401
from . import debugging  # noqa: F401
