"""Distributed environment state.

Parity: `python/paddle/distributed/parallel.py` env accessors
(get_rank/get_world_size, ParallelEnv).  Multi-host identity comes from JAX's
distributed runtime (process_index) or the launcher's env vars
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM are honored for CLI parity).
"""

from __future__ import annotations

import os

import jax

_initialized = False


def is_initialized() -> bool:
    return _initialized


def _mark_initialized():
    global _initialized
    _initialized = True


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(get_rank())
    v = os.environ.get("PADDLE_TRAINER_ID")
    if v is not None:
        return int(v)
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


class ParallelEnv:
    """Reference: `distributed/parallel.py` ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                              "127.0.0.1:6170").split(",")

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
