"""SPMD pipeline parallelism over the 'pp' mesh axis.

This is the TPU-native replacement for the reference's NCCL-p2p pipeline
runtime (`fleet/meta_parallel/pipeline_parallel.py:458`
forward_backward_pipeline + `pp_utils/p2p_communication.py`): instead of
host-driven send/recv, the whole schedule is ONE SPMD program under
shard_map over 'pp' —

* every stage holds its own stage parameters (stacked pytree sharded on 'pp');
* activations move between stages with `lax.ppermute` (compiles to ICI
  collective-permute);
* the microbatch loop runs all ranks every tick with masking (idle ticks are
  the pipeline bubble);
* backward is jax AD through the schedule — ppermute's transpose is the
  reverse permute, so the backward pipeline falls out for free.

The schedule is GPipe/F-then-B at trace level; XLA's latency-hiding scheduler
overlaps the permutes with compute, which recovers most of 1F1B's overlap on
TPU (the 1F1B memory advantage is instead obtained with jax.checkpoint on the
stage fn).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import mesh as _mesh

__all__ = ["pipeline_forward", "stack_stage_params", "pp_sharding"]


def stack_stage_params(per_stage_params: list):
    """Stack a list of identical-structure stage param pytrees along axis 0
    (the 'pp'-sharded leading dim)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                  *per_stage_params)


def pp_sharding(mesh):
    """Sharding for stacked stage params: leading dim on 'pp'."""
    return NamedSharding(mesh, P("pp"))


def pipeline_forward(stage_fn: Callable, params_local: Any, inputs,
                     n_microbatches: int, pp_axis: str = "pp",
                     remat: bool = True):
    """Run the forward pipeline INSIDE shard_map over `pp_axis`.

    stage_fn(params, h) -> h'   (the per-stage computation)
    inputs: [n_microbatches, mb, ...] microbatched activations fed to stage 0
            (same array on every pp rank; only stage 0 reads it).
    Returns [n_microbatches, mb, ...] outputs of the LAST stage (valid on all
    ranks via final broadcast-permute collection).

    Schedule: M + P - 1 ticks; tick t feeds microbatch t into stage 0; stage s
    processes microbatch t - s.  All ranks execute stage_fn every tick.
    """
    P_ = jax.lax.axis_size(pp_axis)
    M = n_microbatches
    idx = jax.lax.axis_index(pp_axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = inputs.shape[1:]
    carry = jnp.zeros(mb_shape, inputs.dtype)  # activation arriving from prev
    outs = jnp.zeros((M,) + mb_shape, inputs.dtype)
    perm_fwd = [(i, (i + 1) % P_) for i in range(P_)]

    for t in range(M + P_ - 1):
        # stage 0 consumes fresh microbatch t (if any); others consume carry
        feed_idx = jnp.clip(t, 0, M - 1)
        first_in = inputs[feed_idx]
        h_in = jnp.where(idx == 0, first_in, carry)
        h_out = fn(params_local, h_in)
        # last stage banks its output for microbatch t - (P-1)
        mb_id = t - (P_ - 1)
        valid_out = (idx == P_ - 1) & (0 <= mb_id) & (mb_id < M)
        bank = jnp.clip(mb_id, 0, M - 1)
        outs = jnp.where(valid_out,
                         outs.at[bank].set(h_out),
                         outs)
        # ship activations to the next stage
        carry = jax.lax.ppermute(h_out, pp_axis, perm_fwd)

    # replicate last-stage outputs to every rank (so loss is SPMD-uniform)
    masked = jnp.where(idx == P_ - 1, outs, jnp.zeros_like(outs))
    outs = jax.lax.psum(masked, pp_axis)
    return outs
