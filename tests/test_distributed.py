"""Distributed stack tests on the virtual 8-device CPU mesh (the "fake
backend" strategy from SURVEY.md §4: real XLA collectives, no TPU pod)."""

import jax

from paddle_tpu.core.jax_compat import shard_map as compat_shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet


@pytest.fixture()
def hybrid_env():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                               "sharding_degree": 2, "sep_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg


def test_mesh_build_and_axes(hybrid_env):
    m = dist.get_mesh()
    assert dict(m.shape) == {"pp": 1, "dp": 2, "sharding": 2, "sep": 1,
                             "mp": 2}
    assert hybrid_env.get_model_parallel_world_size() == 2
    assert hybrid_env.get_data_parallel_world_size() == 2
    assert hybrid_env.get_sharding_parallel_world_size() == 2


def test_mesh_infers_remainder_axis():
    from paddle_tpu.distributed.mesh import build_mesh
    m = build_mesh({"dp": -1, "mp": 2})
    assert m.shape["dp"] == 4 and m.shape["mp"] == 2


def test_topology_comm_lists():
    from paddle_tpu.distributed.fleet import CommunicateTopology
    topo = CommunicateTopology(["data", "model"], [2, 4])
    assert topo.world_size() == 8
    groups = topo.get_comm_list("model")
    assert len(groups) == 2 and len(groups[0]) == 4
    assert topo.get_rank(data=1, model=2) == 6


def test_column_row_parallel_matches_dense(hybrid_env):
    paddle.seed(0)
    col = fleet.ColumnParallelLinear(8, 16, gather_output=False)
    row = fleet.RowParallelLinear(16, 8, input_is_parallel=True)
    x = paddle.randn([4, 8])
    out = row(col(x))
    dense = (x._value @ col.weight._value) @ row.weight._value \
        + row.bias._value + (col.bias._value @ row.weight._value)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(dense),
                               rtol=1e-4, atol=1e-5)
    assert col.weight._value.sharding.spec == P(None, "mp")
    assert row.weight._value.sharding.spec == P("mp", None)


def test_tp_backward_grad_sharded(hybrid_env):
    col = fleet.ColumnParallelLinear(4, 8, gather_output=True)
    out = col(paddle.randn([2, 4]))
    out.sum().backward()
    assert col.weight.grad is not None
    assert col.weight.grad._value.sharding.spec == P(None, "mp")


def test_vocab_parallel_embedding(hybrid_env):
    emb = fleet.VocabParallelEmbedding(64, 16)
    out = emb(paddle.randint(0, 64, [2, 5]))
    assert out.shape == [2, 5, 16]
    out.sum().backward()
    assert emb.weight.grad is not None


def test_data_parallel_batch_sharding(hybrid_env):
    net = nn.Linear(8, 2)
    dp = paddle.DataParallel(net)
    out = dp(paddle.randn([8, 8]))
    assert out._value.sharding.spec == P("dp", None)
    out.sum().backward()
    # grads on replicated params come out replicated (= allreduced)
    assert net.weight.grad._value.sharding.spec == P()


def test_dp_no_sync(hybrid_env):
    net = nn.Linear(4, 2)
    dp = paddle.DataParallel(net)
    with dp.no_sync():
        out = dp(paddle.randn([8, 4]))
    # inside no_sync the batch is NOT dp-sharded
    assert getattr(out._value.sharding, "spec", P()) != P("dp", None)


def test_zero1_sharded_optimizer_state(hybrid_env):
    net = nn.Linear(8, 2)
    opt = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    hopt = fleet.distributed_optimizer(opt)
    net.weight.grad = paddle.randn([8, 2])
    net.bias.grad = paddle.randn([2])
    hopt.step()
    m1 = opt._accumulators["moment1"][id(net.weight)]
    # older jax keeps trailing Nones on PartitionSpec; compare normalized
    assert tuple(s for s in m1.sharding.spec if s is not None) == \
        ("sharding",)
    # bias (size 2, not divisible by shard degree 2? it is) — just exists
    assert id(net.bias) in opt._accumulators["moment1"]


def test_dp_training_matches_single_device(hybrid_env):
    """Golden-loss parity: DP over 2 ranks == single device (same data)."""
    def run(parallel):
        paddle.seed(9)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        model = paddle.DataParallel(net) if parallel else net
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        X = paddle.to_tensor(
            np.random.RandomState(0).rand(16, 4).astype("float32"))
        Y = X.sum(axis=1, keepdim=True)
        losses = []
        for _ in range(5):
            loss = nn.MSELoss()(model(X), Y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


def test_shard_tensor_and_reshard():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    t = dist.shard_tensor(paddle.randn([8, 4]), mesh,
                          [dist.Shard(0), dist.Replicate()])
    assert t._value.sharding.spec == P("x", None)
    r = dist.reshard(t, mesh, [dist.Replicate(), dist.Shard(1)])
    assert r._value.sharding.spec == P(None, "y")
    np.testing.assert_allclose(np.asarray(dist.unshard_dtensor(r)._value),
                               np.asarray(t._value))


def test_placements_api():
    assert dist.Shard(1).get_dim() == 1
    assert dist.Replicate().is_replicated()
    assert dist.Partial().is_partial()
    assert dist.Shard(0) == dist.Shard(0)


def test_shard_layer():
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    net = nn.Linear(8, 8)

    def shard_fn(name, sublayer, m):
        for p in sublayer._parameters.values():
            if p is not None and p.ndim == 2:
                s = dist.shard_tensor(p, m, [dist.Shard(0)])
                p._value = s._value

    dist.shard_layer(net, mesh, shard_fn)
    assert net.weight._value.sharding.spec == P("x", None)


def test_shard_optimizer_inherits_param_sharding():
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    net = nn.Linear(8, 8)
    s = dist.shard_tensor(net.weight, mesh, [dist.Shard(0), dist.Replicate()])
    net.weight._value = s._value
    opt = dist.shard_optimizer(
        optimizer.Adam(learning_rate=0.01, parameters=net.parameters()))
    net.weight.grad = paddle.randn([8, 8])
    net.bias.grad = paddle.randn([8])
    opt.step()
    m1 = opt._inner._accumulators["moment1"][id(net.weight)]
    assert m1.sharding.spec == P("x", None)


def test_collectives_inside_shard_map(hybrid_env):
    m = dist.get_mesh()
    g = dist.new_group(axis="mp")

    def worker(x):
        with dist.axis_context("mp"):
            t = paddle.Tensor._wrap(x)
            dist.all_reduce(t, group=g)
            return t._value

    y = jax.jit(compat_shard_map(worker, mesh=m, in_specs=P("mp"),
                              out_specs=P("mp")))(
        jnp.arange(8, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(y), [4, 6, 8, 10, 4, 6, 8, 10])


def test_allgather_reducescatter_inside_shard_map(hybrid_env):
    m = dist.get_mesh()
    g = dist.new_group(axis="dp")

    def worker(x):
        with dist.axis_context("dp"):
            t = paddle.Tensor._wrap(x)
            outs = []
            dist.all_gather(outs, t, group=g)
            summed = outs[0] + outs[1]
            return summed._value

    x = jnp.arange(8, dtype=jnp.float32)
    y = jax.jit(compat_shard_map(worker, mesh=m, in_specs=P("dp"),
                              out_specs=P("dp")))(x)
    np.testing.assert_allclose(np.asarray(y), [4, 6, 8, 10, 4, 6, 8, 10])


def test_spmd_pipeline_matches_serial():
    from paddle_tpu.distributed.fleet.spmd_pipeline import (
        pipeline_forward, stack_stage_params)
    devs = np.array(jax.devices()[:4]).reshape(4, 1)
    mesh = Mesh(devs, ("pp", "dp"))
    rng = np.random.RandomState(0)
    Ws = [rng.rand(8, 8).astype(np.float32) * 0.1 for _ in range(4)]
    stacked = stack_stage_params([{"w": jnp.asarray(W)} for W in Ws])

    def stage_fn(params, h):
        return jnp.tanh(h @ params["w"])

    M = 3
    x = rng.rand(M, 2, 8).astype(np.float32)

    def pipe(params, inputs):
        local = jax.tree_util.tree_map(lambda a: a[0], params)
        return pipeline_forward(stage_fn, local, inputs, n_microbatches=M)

    out = jax.jit(compat_shard_map(pipe, mesh=mesh, in_specs=(P("pp"), P()),
                                out_specs=P()))(stacked, jnp.asarray(x))
    ref = x.copy()
    for W in Ws:
        ref = np.tanh(ref @ W)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_layer_and_host_schedule(hybrid_env):
    from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                              PipelineParallel)
    paddle.seed(1)
    pipe = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 4, 8), LayerDesc(nn.Tanh),
                LayerDesc(nn.Linear, 8, 4), LayerDesc(nn.Linear, 4, 1)],
        num_stages=2, loss_fn=nn.MSELoss())
    assert pipe.segment_parts == [0, 2, 4]
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs["accumulate_steps"] = 2
    pp = PipelineParallel(pipe, hybrid_env, strategy)
    X = paddle.randn([8, 4])
    Y = X.sum(axis=1, keepdim=True)
    opt = optimizer.SGD(learning_rate=0.05, parameters=pipe.parameters())
    l0 = float(pp.train_batch((X, Y), opt).item())
    # graft-lint: disable=R010 (2-stage toy pipeline; ~1s measured)
    for _ in range(30):
        l = float(pp.train_batch((X, Y), opt).item())
    assert l < l0


def test_shared_layer_desc_ties_weights():
    from paddle_tpu.distributed.fleet import (PipelineLayer, SharedLayerDesc)
    pipe = PipelineLayer(layers=[
        SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
        nn.Tanh(),
        SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4)],
        num_stages=1)
    layers = list(pipe.run_function)
    assert layers[0] is layers[2]


def test_rng_tracker(hybrid_env):
    from paddle_tpu.distributed.fleet import get_rng_state_tracker
    from paddle_tpu.distributed.fleet.random import model_parallel_random_seed
    model_parallel_random_seed(123)
    tracker = get_rng_state_tracker()
    with tracker.rng_state():
        a = paddle.randn([4]).numpy()
    with tracker.rng_state():
        b = paddle.randn([4]).numpy()
    assert not np.array_equal(a, b)  # stateful within the tracker


def test_group_sharded_parallel_api(hybrid_env):
    net = nn.Linear(8, 8)
    opt = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    model, opt2, _ = dist.sharding.group_sharded_parallel(net, opt, "p_g_os")
    assert net.weight._value.sharding.spec[0] == "sharding"


def test_distributed_batch_sampler_epoch_shuffle(hybrid_env):
    from paddle_tpu.io import DistributedBatchSampler

    class DS:
        def __len__(self):
            return 16

    s = DistributedBatchSampler(DS(), 4, num_replicas=2, rank=0, shuffle=True)
    e0 = [i for b in s for i in b]
    s.set_epoch(5)
    e1 = [i for b in s for i in b]
    assert e0 != e1


def test_zero_sharding_uses_any_divisible_dim(hybrid_mesh):
    """A (3, 8) param (dim0 not divisible by sharding=2) must still shard
    on dim 1 instead of silently replicating."""
    import warnings as _w
    from paddle_tpu.distributed.fleet import sharding as shmod

    sh = shmod._shard_spec_for((3, 8))
    assert sh is not None and sh.spec == P(None, "sharding")
    # dim0 divisible: prefers dim0
    sh0 = shmod._shard_spec_for((4, 6))
    assert sh0.spec[0] == "sharding"
    # nothing divisible: warns once, returns None
    shmod._warned_shapes.clear()
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        assert shmod._shard_spec_for((3, 5)) is None
        assert shmod._shard_spec_for((3, 5)) is None
    assert len([r for r in rec if "sharding" in str(r.message)]) == 1


def test_stage2_validates_params(hybrid_mesh):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.sharding import (
        GroupShardedOptimizerStage2)

    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=lin.parameters())
    GroupShardedOptimizerStage2(lin.parameters(), opt)  # ok
    other = paddle.nn.Linear(2, 2)
    with pytest.raises(ValueError):
        GroupShardedOptimizerStage2(other.parameters(), opt)


def test_stage2_offload_places_state_in_host_memory(hybrid_mesh):
    """ZeRO-Offload: optimizer state lives in pinned host memory (the
    jax memory_kind equivalent of the reference's CPU-side Adam)."""
    kinds = {m.kind for m in jax.local_devices()[0].addressable_memories()}
    if "pinned_host" not in kinds:
        pytest.skip("backend exposes no pinned_host memory space "
                    f"(has {sorted(kinds)}); offload degrades to default")
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.sharding import (
        GroupShardedOptimizerStage2)

    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=lin.parameters())
    sharded = GroupShardedOptimizerStage2(lin.parameters(), opt,
                                          offload=True)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(2):  # second step exercises host->device staging too
        loss = (lin(x) * lin(x)).sum()
        loss.backward()
        sharded.step()
        sharded.clear_grad()
    mks = {getattr(a.sharding, "memory_kind", None)
           for accs in opt._accumulators.values()
           for a in accs.values()
           if hasattr(a, "sharding")}
    assert "pinned_host" in mks
    assert np.isfinite(np.asarray(lin.weight._value)).all()


def test_zero_sharding_preserves_tp_layout(hybrid_mesh):
    """A param already mp-sharded on some dim must keep that dim; ZeRO
    goes on a FREE divisible dim (and never double-applies)."""
    from paddle_tpu.distributed.fleet import sharding as shmod
    from paddle_tpu.distributed import mesh as meshmod

    m = meshmod.get_mesh()
    # vocab-parallel style: dim0 mp-sharded, dim1 free and divisible
    existing = NamedSharding(m, P("mp", None))
    sh = shmod._shard_spec_for((30522, 8), existing)
    assert sh is not None
    assert sh.spec[0] == "mp" and sh.spec[1] == "sharding"
    # already ZeRO-sharded: no double application
    assert shmod._shard_spec_for((8, 8), sh) is None
    # every dim taken or indivisible: keeps layout, returns None
    shmod._warned_shapes.clear()
    assert shmod._shard_spec_for((30521,), NamedSharding(m, P("mp"))) is None


def test_stage3_tp_composed_jitted_parity(hybrid_env):
    """ZeRO-3 (params sharded over 'sharding') composed with TP (mp) must
    train to the SAME losses as the unsharded model, with the whole step
    captured by to_static — the sharding lives as layout constraints
    inside one jitted program, not per-step host reshards."""
    from paddle_tpu.jit import to_static

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = fleet.ColumnParallelLinear(8, 16, gather_output=True)
            self.out = nn.Linear(16, 4)

        def forward(self, x):
            return self.out(paddle.nn.functional.relu(self.col(x)))

    def run(stage3):
        paddle.seed(7)
        net = Net()
        opt = optimizer.Adam(learning_rate=0.05,
                             parameters=net.parameters())
        if stage3:
            net, opt, _ = dist.sharding.group_sharded_parallel(
                net, opt, "p_g_os")
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))

        def train_step(xb, yb):
            loss = ((net(xb) - yb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step = to_static(train_step)
        return [float(step(x, y).item()) for _ in range(3)]

    base = run(False)
    sharded = run(True)
    np.testing.assert_allclose(sharded, base, rtol=2e-5, atol=2e-6)
    assert base[-1] < base[0]  # actually trains


def test_stage3_param_layout_survives_jitted_steps(hybrid_env):
    """After jitted updates, stage-3 params must still carry the
    'sharding' axis in their layout (donated outputs keep shardings)."""
    from paddle_tpu.jit import to_static
    paddle.seed(0)
    net = nn.Linear(8, 8)
    opt = optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
    net, opt, _ = dist.sharding.group_sharded_parallel(net, opt, "p_g_os")
    assert net.weight._value.sharding.spec[0] == "sharding"
    x = paddle.to_tensor(np.ones((2, 8), np.float32))

    def train_step(xb):
        loss = (net(xb) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = to_static(train_step)
    for _ in range(2):
        step(x)
    spec = net.weight._value.sharding.spec
    assert "sharding" in tuple(spec), spec


def test_stage2_custom_group_composes_with_tp(hybrid_mesh):
    """VERDICT r3 item 10: custom sharding groups — a group IS a mesh
    axis on TPU — compose eager ZeRO-2 with tensor parallelism: an
    mp-sharded (column-parallel) weight keeps its TP layout while its
    optimizer state and gradients shard over the CUSTOM group axis
    ('dp' here, not the default 'sharding')."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.collective import new_group
    from paddle_tpu.distributed.fleet.sharding import (
        GroupShardedOptimizerStage2)

    mesh = _mesh.get_mesh()
    lin = paddle.nn.Linear(8, 8)
    # TP: column-parallel weight layout over mp
    lin.weight._value = jax.device_put(
        lin.weight._value, NamedSharding(mesh, P(None, "mp")))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=lin.parameters())
    grp = new_group(axis="dp")
    sharded = GroupShardedOptimizerStage2(lin.parameters(), opt, group=grp)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    losses = []
    for _ in range(3):
        loss = ((lin(x) - 1.0) ** 2).mean()
        loss.backward()
        sharded.step()
        sharded.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    # TP layout preserved on the param
    assert "mp" in str(lin.weight._value.sharding.spec)
    # optimizer moments sharded over the CUSTOM axis, composing with mp
    m_acc = opt._accumulators["moment1"][id(lin.weight)]
    spec = m_acc.sharding.spec
    assert "dp" in str(spec), spec
    assert "sharding" not in str(spec), spec


def test_stage2_rejects_rank_list_groups(hybrid_mesh):
    from paddle_tpu.distributed.collective import new_group
    from paddle_tpu.distributed.fleet.sharding import (
        GroupShardedOptimizerStage2)
    import paddle_tpu as paddle
    lin = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=lin.parameters())
    with pytest.raises(ValueError, match="mesh-axis"):
        GroupShardedOptimizerStage2(lin.parameters(), opt,
                                    group=new_group(ranks=[0, 1]))
