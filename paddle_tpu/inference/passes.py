"""Offline optimization passes over saved inference artifacts.

Parity: the reference's save-side conversion utilities —
`paddle.inference.convert_to_mixed_precision`
(`python/paddle/inference/__init__.py`) and the analysis passes of
`fluid/inference/api/analysis_predictor.h:100`.

TPU-native split of responsibilities: graph-level passes the reference
runs in its analysis pipeline (constant folding, fusion, layout) are
XLA's job at predictor compile time — the StableHLO artifact is opaque
and re-optimizing it by hand would fight the compiler.  What remains
OURS is the artifact itself: parameter precision.  These passes rewrite
the saved `.pdiparams.npz` (weights) and record the conversion in
`.pdmeta.json`; `TranslatedLayer` casts at the call boundary, so the
serving program keeps its exported signature while weights occupy half
(bf16/fp16) the HBM — the weight side of the reference's
mixed-precision conversion.
"""

from __future__ import annotations

import json
import shutil

import jax.numpy as jnp
import numpy as np

__all__ = ["convert_to_mixed_precision", "convert_to_int8"]

_DTYPES = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
           "float32": jnp.float32}


def convert_to_mixed_precision(src_prefix: str, dst_prefix: str,
                               mixed_precision: str = "bfloat16",
                               backend: str = "tpu",
                               keep_io_types: bool = True,
                               black_list=None) -> None:
    """Rewrite a `jit.save` artifact with reduced-precision weights.

    Parity: `paddle.inference.convert_to_mixed_precision(src_model,
    src_params, dst_model, dst_params, precision, backend, keep_io_types,
    black_list)` — collapsed to prefix paths (our artifacts derive from
    one prefix).  `black_list`: parameter-name substrings kept at fp32
    (e.g. norm scales).  Delegates to the ONE conversion implementation
    shared with the analysis passes (`analysis.convert_weights_mixed`).
    """
    if mixed_precision not in _DTYPES:
        raise KeyError(mixed_precision)
    from .analysis import Artifact, convert_weights_mixed
    art = Artifact(src_prefix)
    convert_weights_mixed(art.meta, art.params, mixed_precision,
                          black_list)
    art.save(dst_prefix)


def convert_to_int8(src_prefix: str, dst_prefix: str,
                    black_list=None) -> None:
    """Rewrite a `jit.save` artifact with symmetric-absmax INT8 weights.

    Parity: the weight half of the reference's static quantization
    export (`python/paddle/static/quantization/quant2_int8_onednn_pass.py`
    semantics: int8 storage + per-tensor scale, dequantized at the call
    boundary).  Delegates to `analysis.convert_weights_int8` (one
    implementation, also behind the `weight_int8_pass`)."""
    from .analysis import Artifact, convert_weights_int8
    art = Artifact(src_prefix)
    convert_weights_int8(art.meta, art.params, black_list)
    art.save(dst_prefix)
