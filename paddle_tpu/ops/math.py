"""Elementwise + reduction math ops.

Parity target: `python/paddle/tensor/math.py` + `ops.py` (reference wraps
`_C_ops.*`; here every op's "kernel" is its jnp/lax lowering, registered in
ops/registry.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import dispatch as _d, primitive, register_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "scale", "neg", "abs", "sign", "sqrt", "rsqrt",
    "square", "reciprocal", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "floor", "ceil", "round", "trunc", "frac",
    "erf", "erfinv", "lgamma", "digamma", "clip", "maximum", "minimum",
    "fmax", "fmin", "atan2", "hypot", "logit", "nan_to_num",
    "sum", "mean", "max", "min", "prod", "logsumexp", "amax", "amin",
    "std", "var", "cumsum", "cumprod", "cummax", "cummin", "add_n",
    "isnan", "isinf", "isfinite", "nansum", "nanmean", "count_nonzero",
    "diff", "sgn", "trace", "inner", "outer", "heaviside", "rad2deg", "deg2rad",
    "lerp", "addmm", "increment", "stanh", "multiplex", "gcd", "lcm",
]


def _binary(op_name, jfn):
    register_op(op_name, jfn)

    def fn(x, y, name=None, _op=op_name):
        return _d(_op, (x, y), {})
    fn.__name__ = op_name
    return fn


add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
pow_ = _binary("pow", jnp.power)


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return pow_(x, y)


float_power = _binary("float_power", lambda x, y: jnp.float_power(x, y))


def _unary(op_name, jfn):
    register_op(op_name, jfn)

    def fn(x, name=None, _op=op_name):
        return _d(_op, (x,), {})
    fn.__name__ = op_name
    return fn


neg = _unary("neg", jnp.negative)
abs = _unary("abs", jnp.abs)  # noqa: A001
sign = _unary("sign", jnp.sign)
sgn = sign
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", jnp.reciprocal)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
# paddle rounds half away from zero, not banker's rounding
round = _unary("round", lambda x: jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5))  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
logit_ = _unary("logit", jax.scipy.special.logit)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def logit(x, eps=None, name=None):
    if eps is not None:
        from . import manipulation as _m
        x = clip(x, eps, 1.0 - eps)
    return logit_(x)


register_op("stanh", lambda x, *, scale_a, scale_b: scale_b * jnp.tanh(scale_a * x))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _d("stanh", (x,), {"scale_a": scale_a, "scale_b": scale_b})


register_op("scale", lambda x, *, scale, bias, bias_after_scale:
            x * scale + bias if bias_after_scale else (x + bias) * scale)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _d("scale", (x,), {"scale": float(scale), "bias": float(bias),
                             "bias_after_scale": bool(bias_after_scale)})
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


register_op("clip", lambda x, *, min, max: jnp.clip(x, min, max))


def clip(x, min=None, max=None, name=None):
    from ..framework.tensor import Tensor
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return _d("clip", (x,), {"min": mn, "max": mx})


register_op("nan_to_num", lambda x, *, nan, posinf, neginf:
            jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _d("nan_to_num", (x,), {"nan": nan, "posinf": posinf, "neginf": neginf})


register_op("lerp", lambda x, y, w: x + w * (y - x))


def lerp(x, y, weight, name=None):
    return _d("lerp", (x, y, weight), {})


register_op("addmm", lambda input, x, y, *, beta, alpha:
            beta * input + alpha * (x @ y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _d("addmm", (input, x, y), {"beta": beta, "alpha": alpha})


# ---------------------------------------------------------------- reductions
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(op_name, jfn, has_dtype=False):
    if has_dtype:
        register_op(op_name, lambda x, *, axis, keepdim, dtype:
                    jfn(x, axis=axis, keepdims=keepdim, dtype=dtype))

        def fn(x, axis=None, dtype=None, keepdim=False, name=None, _op=op_name):
            from ..core.dtypes import convert_dtype
            return _d(_op, (x,), {"axis": _axis_arg(axis), "keepdim": bool(keepdim),
                                  "dtype": convert_dtype(dtype)})
    else:
        register_op(op_name, lambda x, *, axis, keepdim:
                    jfn(x, axis=axis, keepdims=keepdim))

        def fn(x, axis=None, keepdim=False, name=None, _op=op_name):
            return _d(_op, (x,), {"axis": _axis_arg(axis), "keepdim": bool(keepdim)})
    fn.__name__ = op_name
    return fn


sum = _reduce("sum", jnp.sum, has_dtype=True)  # noqa: A001
mean = _reduce("mean", jnp.mean)
max = _reduce("max", jnp.max)  # noqa: A001
min = _reduce("min", jnp.min)  # noqa: A001
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
prod = _reduce("prod", jnp.prod, has_dtype=True)
logsumexp = _reduce("logsumexp", lambda x, axis, keepdims:
                    jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
nansum = _reduce("nansum", jnp.nansum, has_dtype=True)
nanmean = _reduce("nanmean", jnp.nanmean)

register_op("std", lambda x, *, axis, unbiased, keepdim:
            jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))
register_op("var", lambda x, *, axis, unbiased, keepdim:
            jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _d("std", (x,), {"axis": _axis_arg(axis), "unbiased": bool(unbiased),
                            "keepdim": bool(keepdim)})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _d("var", (x,), {"axis": _axis_arg(axis), "unbiased": bool(unbiased),
                            "keepdim": bool(keepdim)})


register_op("count_nonzero", lambda x, *, axis, keepdim:
            jnp.count_nonzero(x, axis=axis, keepdims=keepdim))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _d("count_nonzero", (x,), {"axis": _axis_arg(axis), "keepdim": keepdim})


register_op("cumsum", lambda x, *, axis: jnp.cumsum(x, axis=axis))
register_op("cumprod", lambda x, *, axis: jnp.cumprod(x, axis=axis))


def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        from . import manipulation as _m
        x = _m.flatten(x)
        axis = 0
    out = _d("cumsum", (x,), {"axis": int(axis)})
    if dtype is not None:
        from . import manipulation as _m
        out = _m.cast(out, dtype)
    return out


def cumprod(x, dim=None, dtype=None, name=None):
    if dim is None:
        from . import manipulation as _m
        x = _m.flatten(x)
        dim = 0
    out = _d("cumprod", (x,), {"axis": int(dim)})
    if dtype is not None:
        from . import manipulation as _m
        out = _m.cast(out, dtype)
    return out


register_op("cummax_val", lambda x, *, axis: jax.lax.cummax(x, axis=axis))
register_op("cummin_val", lambda x, *, axis: jax.lax.cummin(x, axis=axis))


def cummax(x, axis=None, dtype="int64", name=None):
    axis = -1 if axis is None else int(axis)
    val = _d("cummax_val", (x,), {"axis": axis % x.ndim if axis < 0 else axis})
    return val, None  # indices path provided in search.cummax_with_indices


def cummin(x, axis=None, dtype="int64", name=None):
    axis = -1 if axis is None else int(axis)
    val = _d("cummin_val", (x,), {"axis": axis % x.ndim if axis < 0 else axis})
    return val, None


register_op("add_n", lambda xs: functools_reduce(xs))


def functools_reduce(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return _d("add_n", (list(inputs),), {})


register_op("trace", lambda x, *, offset, axis1, axis2:
            jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _d("trace", (x,), {"offset": offset, "axis1": axis1, "axis2": axis2})


register_op("inner", lambda x, y: jnp.inner(x, y))
register_op("outer", lambda x, y: jnp.outer(x, y))


def inner(x, y, name=None):
    return _d("inner", (x, y), {})


def outer(x, y, name=None):
    return _d("outer", (x, y), {})


register_op("diff", lambda x, *, n, axis: jnp.diff(x, n=n, axis=axis))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _d("diff", (x,), {"n": n, "axis": axis})


def increment(x, value=1.0, name=None):
    x.set_value(x._value + value)
    return x


register_op("multiplex", lambda inputs, index:
            jnp.take_along_axis(jnp.stack(inputs, axis=0),
                                index.reshape(1, -1, 1).astype(jnp.int32),
                                axis=0)[0])


def multiplex(inputs, index, name=None):
    return _d("multiplex", (list(inputs), index), {})
