"""fleet.utils — sequence parallel, recompute helpers.
Parity: `python/paddle/distributed/fleet/utils/`."""

from . import sequence_parallel_utils  # noqa: F401
from .sequence_parallel_utils import (  # noqa: F401
    AllGatherOp, ColumnSequenceParallelLinear, GatherOp, ReduceScatterOp,
    RowSequenceParallelLinear, ScatterOp, all_gather,
    is_sequence_parallel_parameter, mark_as_sequence_parallel_parameter,
    reduce_scatter, register_sequence_parallel_allreduce_hooks, scatter)
