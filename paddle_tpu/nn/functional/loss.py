"""Loss functionals. Parity: `python/paddle/nn/functional/loss.py`
(cross_entropy is the reference's softmax_with_cross_entropy fused op —
here one fused XLA expression with the same soft_label / ignore_index /
label_smoothing semantics)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.registry import dispatch as _d, register_op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "square_error_cost",
    "log_loss", "triplet_margin_loss", "sigmoid_focal_loss",
]


def _reduce_loss(loss_val, reduction):
    if reduction == "mean":
        return jnp.mean(loss_val)
    if reduction == "sum":
        return jnp.sum(loss_val)
    return loss_val


def _ce_impl(logits, label, weight, *, soft_label, ignore_index, reduction,
             axis, label_smoothing, use_softmax):
    num_classes = logits.shape[axis]
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        target = label
        if label_smoothing > 0:
            target = target * (1 - label_smoothing) + label_smoothing / num_classes
        per = -jnp.sum(target * logp, axis=axis)
        valid = None
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        valid = (lbl != ignore_index)
        safe = jnp.where(valid, lbl, 0).astype(jnp.int32)
        target = jax.nn.one_hot(safe, num_classes, axis=axis, dtype=logp.dtype)
        if label_smoothing > 0:
            target = target * (1 - label_smoothing) + label_smoothing / num_classes
        per = -jnp.sum(target * logp, axis=axis)
        if weight is not None:
            per = per * jnp.take(weight, safe)
        per = jnp.where(valid, per, 0.0)
    if reduction == "mean":
        if valid is not None:
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            if weight is not None:
                denom = jnp.maximum(jnp.sum(
                    jnp.where(valid, jnp.take(weight, safe), 0.0)), 1e-12)
            return jnp.sum(per) / denom
        return jnp.mean(per)
    if reduction == "sum":
        return jnp.sum(per)
    return per


register_op("cross_entropy", _ce_impl, tags=("fused",))


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    return _d("cross_entropy", (input, label, weight),
              {"soft_label": bool(soft_label), "ignore_index": int(ignore_index),
               "reduction": reduction, "axis": int(axis),
               "label_smoothing": float(label_smoothing),
               "use_softmax": bool(use_softmax)})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


register_op("nll_loss", lambda logp, label, weight, *, ignore_index, reduction:
            _nll_impl(logp, label, weight, ignore_index, reduction))


def _nll_impl(logp, label, weight, ignore_index, reduction):
    # logp: [N, C, *spatial], label: [N, *spatial] (paddle N-D semantics)
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0).astype(jnp.int32)
    per = -jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
    per = jnp.squeeze(per, axis=1)
    w = jnp.take(weight, safe) if weight is not None else 1.0
    per = jnp.where(valid, per * w, 0.0)
    if reduction == "mean":
        denom = jnp.sum(jnp.where(valid, w, 0.0)) if weight is not None else \
            jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
        return jnp.sum(per) / denom
    return _reduce_loss(per, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    return _d("nll_loss", (input, label, weight),
              {"ignore_index": int(ignore_index), "reduction": reduction})


register_op("mse_loss", lambda x, y, *, reduction:
            _reduce_loss(jnp.square(x - y), reduction))


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _d("mse_loss", (input, label), {"reduction": reduction})


def square_error_cost(input, label):  # noqa: A002
    return _d("mse_loss", (input, label), {"reduction": "none"})


register_op("l1_loss", lambda x, y, *, reduction:
            _reduce_loss(jnp.abs(x - y), reduction))


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _d("l1_loss", (input, label), {"reduction": reduction})


register_op("smooth_l1_loss", lambda x, y, *, reduction, delta:
            _reduce_loss(jnp.where(jnp.abs(x - y) <= delta,
                                   0.5 * jnp.square(x - y),
                                   delta * (jnp.abs(x - y) - 0.5 * delta)),
                         reduction))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    return _d("smooth_l1_loss", (input, label),
              {"reduction": reduction, "delta": float(delta)})


register_op("bce", lambda x, y, w, *, reduction:
            _reduce_loss((-(y * jnp.log(jnp.maximum(x, 1e-12))
                            + (1 - y) * jnp.log(jnp.maximum(1 - x, 1e-12))))
                         * (w if w is not None else 1.0), reduction))


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    return _d("bce", (input, label, weight), {"reduction": reduction})


def _bce_logits_impl(x, y, w, pos_w, *, reduction):
    # numerically stable: max(x,0) - x*y + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if pos_w is not None:
        log_w = (pos_w - 1) * y + 1
        loss = loss * log_w
    if w is not None:
        loss = loss * w
    return _reduce_loss(loss, reduction)


register_op("bce_with_logits", _bce_logits_impl)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return _d("bce_with_logits", (logit, label, weight, pos_weight),
              {"reduction": reduction})


register_op("kl_div", lambda x, y, *, reduction, log_target:
            _reduce_loss(jnp.exp(y) * (y - x) if log_target
                         else y * (jnp.log(jnp.maximum(y, 1e-12)) - x),
                         reduction))


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    # paddle semantics: input is log-probabilities
    out = _d("kl_div", (input, label), {"reduction": "none",
                                        "log_target": bool(log_target)})
    from ...ops import math as _math
    if reduction == "mean":
        return _math.mean(out)
    if reduction == "sum":
        return _math.sum(out)
    if reduction == "batchmean":
        return _math.sum(out) / out.shape[0]
    return out


register_op("margin_ranking_loss", lambda x1, x2, y, *, margin, reduction:
            _reduce_loss(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return _d("margin_ranking_loss", (input, other, label),
              {"margin": float(margin), "reduction": reduction})


register_op("cosine_embedding_loss", lambda x1, x2, y, *, margin, reduction:
            _cos_emb_impl(x1, x2, y, margin, reduction))


def _cos_emb_impl(x1, x2, y, margin, reduction):
    cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce_loss(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    return _d("cosine_embedding_loss", (input1, input2, label),
              {"margin": float(margin), "reduction": reduction})


register_op("hinge_embedding_loss", lambda x, y, *, margin, reduction:
            _reduce_loss(jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)),
                         reduction))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return _d("hinge_embedding_loss", (input, label),
              {"margin": float(margin), "reduction": reduction})


register_op("log_loss", lambda pred, label, *, epsilon:
            -label * jnp.log(pred + epsilon)
            - (1 - label) * jnp.log(1 - pred + epsilon))


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return _d("log_loss", (input, label), {"epsilon": float(epsilon)})


def _triplet_impl(a, p, n, *, margin, pnorm, reduction):
    dp = jnp.linalg.norm(a - p, ord=pnorm, axis=-1)
    dn = jnp.linalg.norm(a - n, ord=pnorm, axis=-1)
    return _reduce_loss(jnp.maximum(0.0, dp - dn + margin), reduction)


register_op("triplet_margin_loss", _triplet_impl)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    return _d("triplet_margin_loss", (input, positive, negative),
              {"margin": float(margin), "pnorm": p, "reduction": reduction})


def _focal_impl(logit, label, norm, *, alpha, gamma, reduction):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if norm is not None:
        loss = loss / norm
    return _reduce_loss(loss, reduction)


register_op("sigmoid_focal_loss", _focal_impl)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return _d("sigmoid_focal_loss", (logit, label, normalizer),
              {"alpha": float(alpha), "gamma": float(gamma),
               "reduction": reduction})
