from . import datasets, models, transforms  # noqa: F401
