"""Distribution zoo: sampling moments, densities vs scipy, KL rules,
reparameterized gradients.

Mirrors the reference's `test/distribution/test_distribution_*.py` strategy
(moment checks on large samples, log_prob against scipy, KL closed forms).
"""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Beta, Categorical, Dirichlet,
                                     Exponential, Gamma, Geometric, Gumbel,
                                     Laplace, LogNormal, Multinomial, Normal,
                                     Poisson, Uniform, kl_divergence,
                                     register_kl)

N = 20000


def _np(t):
    return np.asarray(t._value)


def check_moments(dist, ref_mean, ref_var, rtol=0.12):
    s = _np(dist.sample([N]))
    np.testing.assert_allclose(s.mean(axis=0), ref_mean, rtol=rtol,
                               atol=0.05)
    np.testing.assert_allclose(s.var(axis=0), ref_var, rtol=max(rtol, 0.15),
                               atol=0.08)
    np.testing.assert_allclose(_np(dist.mean), ref_mean, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(_np(dist.variance), ref_var, rtol=1e-5,
                               atol=1e-6)


def test_normal():
    d = Normal(1.5, 2.0)
    check_moments(d, 1.5, 4.0)
    x = np.array([0.0, 1.0, 3.3], np.float32)
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               st.norm(1.5, 2.0).logpdf(x), rtol=1e-5)
    np.testing.assert_allclose(_np(d.cdf(paddle.to_tensor(x))),
                               st.norm(1.5, 2.0).cdf(x), rtol=1e-5)
    np.testing.assert_allclose(float(_np(d.entropy())),
                               st.norm(1.5, 2.0).entropy(), rtol=1e-6)


def test_uniform():
    d = Uniform(-1.0, 3.0)
    check_moments(d, 1.0, 16.0 / 12.0)
    x = np.array([-0.5, 2.9], np.float32)
    np.testing.assert_allclose(_np(d.log_prob(paddle.to_tensor(x))),
                               st.uniform(-1, 4).logpdf(x), rtol=1e-6)
    assert _np(d.log_prob(paddle.to_tensor(np.float32(5.0)))) == -np.inf


def test_bernoulli_categorical():
    b = Bernoulli(0.3)
    s = _np(b.sample([N]))
    assert abs(s.mean() - 0.3) < 0.02
    np.testing.assert_allclose(float(_np(b.entropy())),
                               st.bernoulli(0.3).entropy(), rtol=1e-5)

    logits = np.log(np.array([0.2, 0.5, 0.3], np.float32))
    c = Categorical(logits)
    s = _np(c.sample([N]))
    freq = np.bincount(s.astype(int), minlength=3) / N
    np.testing.assert_allclose(freq, [0.2, 0.5, 0.3], atol=0.02)
    np.testing.assert_allclose(
        _np(c.log_prob(paddle.to_tensor(np.array([0, 1, 2])))),
        np.log([0.2, 0.5, 0.3]), rtol=1e-5)
    np.testing.assert_allclose(float(_np(c.entropy())),
                               st.entropy([0.2, 0.5, 0.3]), rtol=1e-5)


def test_beta_gamma_dirichlet():
    be = Beta(2.0, 5.0)
    check_moments(be, 2 / 7, (2 * 5) / (49 * 8.0))
    x = np.array([0.1, 0.4], np.float32)
    np.testing.assert_allclose(_np(be.log_prob(paddle.to_tensor(x))),
                               st.beta(2, 5).logpdf(x), rtol=1e-4)

    g = Gamma(3.0, 2.0)
    check_moments(g, 1.5, 0.75)
    np.testing.assert_allclose(_np(g.log_prob(paddle.to_tensor(x))),
                               st.gamma(3, scale=0.5).logpdf(x), rtol=1e-4)

    dr = Dirichlet(np.array([1.0, 2.0, 3.0], np.float32))
    s = _np(dr.sample([N]))
    np.testing.assert_allclose(s.mean(axis=0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.02)
    np.testing.assert_allclose(s.sum(axis=-1), 1.0, rtol=1e-5)
    v = np.array([0.2, 0.3, 0.5], np.float32)
    np.testing.assert_allclose(float(_np(dr.log_prob(paddle.to_tensor(v)))),
                               st.dirichlet([1, 2, 3]).logpdf(v), rtol=1e-4)


def test_laplace_exponential_lognormal_gumbel():
    la = Laplace(0.5, 2.0)
    check_moments(la, 0.5, 8.0)
    x = np.array([-1.0, 2.0], np.float32)
    np.testing.assert_allclose(_np(la.log_prob(paddle.to_tensor(x))),
                               st.laplace(0.5, 2.0).logpdf(x), rtol=1e-5)

    ex = Exponential(2.0)
    check_moments(ex, 0.5, 0.25)
    np.testing.assert_allclose(
        _np(ex.log_prob(paddle.to_tensor(np.abs(x)))),
        st.expon(scale=0.5).logpdf(np.abs(x)), rtol=1e-5)

    ln = LogNormal(0.0, 0.5)
    want_mean = np.exp(0.125)
    s = _np(ln.sample([N]))
    assert abs(s.mean() - want_mean) < 0.05
    np.testing.assert_allclose(
        _np(ln.log_prob(paddle.to_tensor(np.abs(x)))),
        st.lognorm(0.5).logpdf(np.abs(x)), rtol=1e-4)

    gu = Gumbel(1.0, 2.0)
    s = _np(gu.sample([N]))
    assert abs(s.mean() - (1.0 + 2.0 * 0.5772156649)) < 0.1
    np.testing.assert_allclose(_np(gu.log_prob(paddle.to_tensor(x))),
                               st.gumbel_r(1.0, 2.0).logpdf(x), rtol=1e-4)


def test_geometric_poisson_multinomial():
    ge = Geometric(0.25)
    s = _np(ge.sample([N]))
    assert abs(s.mean() - 3.0) < 0.15
    k = np.array([0.0, 3.0], np.float32)
    np.testing.assert_allclose(_np(ge.log_prob(paddle.to_tensor(k))),
                               st.geom(0.25, loc=-1).logpmf(k), rtol=1e-5)

    po = Poisson(4.0)
    s = _np(po.sample([N]))
    assert abs(s.mean() - 4.0) < 0.1
    np.testing.assert_allclose(_np(po.log_prob(paddle.to_tensor(k))),
                               st.poisson(4.0).logpmf(k), rtol=1e-5)

    mu = Multinomial(10, np.array([0.2, 0.3, 0.5], np.float32))
    s = _np(mu.sample([N // 10]))
    assert s.shape == (N // 10, 3)
    np.testing.assert_allclose(s.sum(-1), 10.0)
    np.testing.assert_allclose(s.mean(axis=0), [2.0, 3.0, 5.0], rtol=0.1)
    v = np.array([2.0, 3.0, 5.0], np.float32)
    np.testing.assert_allclose(
        float(_np(mu.log_prob(paddle.to_tensor(v)))),
        st.multinomial(10, [0.2, 0.3, 0.5]).logpmf(v), rtol=1e-4)


def test_kl_closed_forms_match_monte_carlo():
    pairs = [
        (Normal(0.0, 1.0), Normal(1.0, 2.0)),
        (Beta(2.0, 3.0), Beta(4.0, 2.0)),
        (Gamma(2.0, 1.0), Gamma(3.0, 2.0)),
        (Exponential(1.0), Exponential(3.0)),
        (Laplace(0.0, 1.0), Laplace(0.5, 2.0)),
    ]
    for p, q in pairs:
        kl = float(_np(kl_divergence(p, q)))
        s = p.sample([50000])
        mc = float(_np(paddle.mean(p.log_prob(s) - q.log_prob(s))))
        assert abs(kl - mc) < max(0.05, 0.1 * abs(kl)), \
            (type(p).__name__, kl, mc)
    # categorical / bernoulli / dirichlet exact
    c1 = Categorical(np.log(np.array([0.5, 0.5], np.float32)))
    c2 = Categorical(np.log(np.array([0.9, 0.1], np.float32)))
    want = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
    np.testing.assert_allclose(float(_np(kl_divergence(c1, c2))), want,
                               rtol=1e-5)


def test_kl_unregistered_raises_and_register_works():
    class Weird(Normal):
        pass

    # subclass dispatch falls back to the Normal rule
    k = kl_divergence(Weird(0.0, 1.0), Normal(0.0, 1.0))
    assert abs(float(_np(k))) < 1e-6

    class Alien(paddle.distribution.Distribution):
        pass

    with pytest.raises(NotImplementedError):
        kl_divergence(Alien(), Alien())

    @register_kl(Alien, Alien)
    def _kl(p, q):
        return paddle.to_tensor(np.float32(42.0))

    assert float(_np(kl_divergence(Alien(), Alien()))) == 42.0


def test_rsample_pathwise_gradients():
    """d/d(mu,sigma) E[x^2] for x~N(mu,sigma): exact (2mu, 2sigma)."""
    paddle.seed(7)
    mu = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    sigma = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
    d = Normal(mu, sigma)
    x = d.rsample([100000])
    loss = paddle.mean(x * x)
    loss.backward()
    assert abs(float(_np(mu.grad)) - 2.0) < 0.05
    assert abs(float(_np(sigma.grad)) - 1.0) < 0.05


def test_bernoulli_rsample_has_gradients():
    from paddle_tpu.framework.tensor import Parameter
    p = Parameter(np.float32(0.4))
    d = Bernoulli(p)
    hard = _np(d.sample([1000]))
    assert set(np.unique(hard)) <= {0.0, 1.0}
    soft = d.rsample([1000], temperature=0.3)
    loss = paddle.mean(soft)
    loss.backward()
    assert p.grad is not None and abs(float(_np(p.grad))) > 1e-4


def test_batch_distributions_broadcast():
    d = Normal(np.zeros(3, np.float32), np.ones(3, np.float32) * 2.0)
    assert d.batch_shape == (3,)
    s = d.sample([5])
    assert tuple(s.shape) == (5, 3)
    lp = d.log_prob(paddle.to_tensor(np.zeros(3, np.float32)))
    assert tuple(lp.shape) == (3,)
