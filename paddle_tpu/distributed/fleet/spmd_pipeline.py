"""SPMD pipeline parallelism over the 'pp' mesh axis.

This is the TPU-native replacement for the reference's NCCL-p2p pipeline
runtime (`fleet/meta_parallel/pipeline_parallel.py:458`
forward_backward_pipeline + `pp_utils/p2p_communication.py`): instead of
host-driven send/recv, the whole schedule is ONE SPMD program under
shard_map over 'pp' —

* every stage holds its own stage parameters (stacked pytree sharded on 'pp');
* activations move between stages with `lax.ppermute` (compiles to ICI
  collective-permute);
* the microbatch loop runs all ranks every tick with masking (idle ticks are
  the pipeline bubble);
* backward is jax AD through the schedule — ppermute's transpose is the
  reverse permute, so the backward pipeline falls out for free.

The schedule is GPipe/F-then-B at trace level; XLA's latency-hiding scheduler
overlaps the permutes with compute, which recovers most of 1F1B's overlap on
TPU (the 1F1B memory advantage is instead obtained with jax.checkpoint on the
stage fn).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.jax_compat import axis_size as _axis_size, \
    pvary as _compat_pvary
from .. import mesh as _mesh

__all__ = ["pipeline_forward", "interleaved_pipeline_forward",
           "stack_stage_params", "pp_sharding"]


def stack_stage_params(per_stage_params: list):
    """Stack a list of identical-structure stage param pytrees along axis 0
    (the 'pp'-sharded leading dim)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                  *per_stage_params)


def pp_sharding(mesh):
    """Sharding for stacked stage params: leading dim on 'pp'."""
    return NamedSharding(mesh, P("pp"))


def pipeline_forward(stage_fn: Callable, params_local: Any, inputs,
                     n_microbatches: int, pp_axis: str = "pp",
                     remat: bool = True):
    """Run the forward pipeline INSIDE shard_map over `pp_axis`.

    stage_fn(params, h) -> h'   (the per-stage computation)
    inputs: [n_microbatches, mb, ...] microbatched activations fed to stage 0
            (same array on every pp rank; only stage 0 reads it).
    Returns [n_microbatches, mb, ...] outputs of the LAST stage (valid on all
    ranks via final broadcast-permute collection).

    Schedule: M + P - 1 ticks; tick t feeds microbatch t into stage 0; stage s
    processes microbatch t - s.  All ranks execute stage_fn every tick.
    """
    P_ = _axis_size(pp_axis)
    M = n_microbatches
    idx = jax.lax.axis_index(pp_axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = inputs.shape[1:]
    carry0 = jnp.zeros(mb_shape, inputs.dtype)  # activation from prev stage
    outs0 = jnp.zeros((M,) + mb_shape, inputs.dtype)
    perm_fwd = [(i, (i + 1) % P_) for i in range(P_)]
    # jax_compat.pvary dispatches pcast/pvary and no-ops on pre-vma jax
    carry0 = _compat_pvary(carry0, (pp_axis,))
    outs0 = _compat_pvary(outs0, (pp_axis,))

    def tick(state, t):
        carry, outs = state
        # stage 0 consumes fresh microbatch t (if any); others the carry
        first_in = inputs[jnp.clip(t, 0, M - 1)]
        h_in = jnp.where(idx == 0, first_in, carry)
        h_out = fn(params_local, h_in)
        # last stage banks its output for microbatch t - (P-1)
        mb_id = t - (P_ - 1)
        valid_out = (idx == P_ - 1) & (0 <= mb_id) & (mb_id < M)
        bank = jnp.clip(mb_id, 0, M - 1)
        outs = jnp.where(valid_out, outs.at[bank].set(h_out), outs)
        # ship activations to the next stage
        carry = jax.lax.ppermute(h_out, pp_axis, perm_fwd)
        return (carry, outs), None

    # scan keeps the traced program size constant in M (one tick body)
    (_, outs), _ = jax.lax.scan(tick, (carry0, outs0),
                                jnp.arange(M + P_ - 1))

    # replicate last-stage outputs to every rank (so loss is SPMD-uniform)
    masked = jnp.where(idx == P_ - 1, outs, jnp.zeros_like(outs))
    outs = jax.lax.psum(masked, pp_axis)
    return outs


def interleaved_pipeline_forward(stage_fn: Callable, chunk_params_local: Any,
                                 inputs, n_microbatches: int,
                                 n_chunks: int, pp_axis: str = "pp",
                                 remat: bool = True):
    """Interleaved / virtual-pipeline (VPP) schedule inside shard_map.

    Parity: `fleet/meta_parallel/pipeline_parallel.py:986`
    (PipelineParallelWithInterleave) — re-designed as one SPMD program.

    Each pp rank owns `n_chunks` (=V) model chunks; global stage
    g = v*P + r lives on rank r, chunk v (the Megatron interleaved
    assignment).  Microbatch m enters the 0th stage at tick
    s_m = (m // P) * P * V + (m % P); activations advance one global stage
    per tick, so every rank computes exactly ONE chunk per tick and the
    bubble shrinks from (P-1)/(M+P-1) stage-units to ~(P-1)/(M*V) chunk
    units — the VPP win, with the p2p rides on ICI collective-permutes.

    chunk_params_local: pytree whose leaves have leading dim V — this
    rank's V chunk parameter sets (from a (V, P, ...) global stack with P
    on the pp axis).
    stage_fn(chunk_params, h) -> h' for ONE chunk.
    inputs: [M, mb, ...]; returns [M, mb, ...] last-global-stage outputs.
    """
    P_ = _axis_size(pp_axis)
    M, V = n_microbatches, n_chunks
    idx = jax.lax.axis_index(pp_axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    mb_shape = inputs.shape[1:]
    carry0 = jnp.zeros(mb_shape, inputs.dtype)
    outs0 = jnp.zeros((M,) + mb_shape, inputs.dtype)
    perm_fwd = [(i, (i + 1) % P_) for i in range(P_)]
    # jax_compat.pvary dispatches pcast/pvary and no-ops on pre-vma jax
    carry0 = _compat_pvary(carry0, (pp_axis,))
    outs0 = _compat_pvary(outs0, (pp_axis,))
    # exact tick count: the last microbatch enters at s_{M-1} =
    # ((M-1)//P)*P*V + (M-1)%P and needs P*V ticks to drain
    total_ticks = ((M - 1) // P_) * P_ * V + (M - 1) % P_ + P_ * V

    def tick(state, t):
        carry, outs = state
        # which (microbatch, global stage) does THIS rank hold right now?
        j = (t - idx) % P_                     # in-round microbatch offset
        k = (t - idx - j) // (P_ * V)          # round index
        m = k * P_ + j
        g = t - (k * P_ * V + j)               # global stage position
        v = jnp.clip(g // P_, 0, V - 1)        # chunk on this rank
        valid = (k >= 0) & (m < M) & (g >= 0) & (g < P_ * V)

        params_v = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, v, axis=0), chunk_params_local)
        fresh = inputs[jnp.clip(m, 0, M - 1)]
        h_in = jnp.where((idx == 0) & (g == 0), fresh, carry)
        h_out = fn(params_v, h_in)
        h_out = jnp.where(valid, h_out, jnp.zeros_like(h_out))

        # last global stage banks its microbatch's output
        is_last = valid & (g == P_ * V - 1)
        bank = jnp.clip(m, 0, M - 1)
        outs = jnp.where(is_last, outs.at[bank].set(h_out), outs)
        carry = jax.lax.ppermute(h_out, pp_axis, perm_fwd)
        return (carry, outs), None

    (_, outs), _ = jax.lax.scan(tick, (carry0, outs0),
                                jnp.arange(total_ticks))
    masked = jnp.where(idx == P_ - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(masked, pp_axis)
