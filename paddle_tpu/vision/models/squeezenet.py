"""SqueezeNet. Parity: `python/paddle/vision/models/squeezenet.py`."""

from __future__ import annotations

from ... import nn
from ...ops import manipulation as _m

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, inplanes, squeeze_planes, expand1x1_planes,
                 expand3x3_planes):
        super().__init__()
        self.squeeze = nn.Conv2D(inplanes, squeeze_planes, 1)
        self.expand1x1 = nn.Conv2D(squeeze_planes, expand1x1_planes, 1)
        self.expand3x3 = nn.Conv2D(squeeze_planes, expand3x3_planes, 3,
                                   padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return _m.concat([self.relu(self.expand1x1(x)),
                          self.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        else:
            raise ValueError("version must be '1.0' or '1.1'")
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1),
                nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return _m.flatten(x, start_axis=1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
