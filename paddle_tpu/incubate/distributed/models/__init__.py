"""Parity: `python/paddle/incubate/distributed/`."""
