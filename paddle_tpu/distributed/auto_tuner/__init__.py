"""Parallel-strategy auto-tuner.

Parity: `python/paddle/distributed/auto_tuner/` (tuner.py AutoTuner,
prune.py rules, search.py GridSearch) — the reference launches trial jobs
over candidate (dp, mp, pp, sharding, micro-batch) configs and keeps the
fastest; here trials are user-supplied callables (typically: jit-compile
the hybrid step on tiny shapes with `dryrun`-style meshes and time one
step), and the same divisibility/memory prune rules cut the space first.
"""

from .tuner import AutoTuner, Trial, default_candidates, prune_by_memory

__all__ = ["AutoTuner", "Trial", "default_candidates", "prune_by_memory"]
from .cost_model import (Hardware, ModelSpec, estimate_memory,  # noqa: F401
                         estimate_params, estimate_step_time,
                         prune_by_model, rank_candidates)
