"""RNG state tracker for tensor parallelism.

Parity: `python/paddle/distributed/fleet/layers/mpu/random.py`
(RNGStatesTracker + model_parallel_rng contexts → consistent dropout across
TP ranks).  TPU-native: a named state is a fold_in of the mp axis index (or
not) into the active key source — mp-local states differ per rank, global
states match.
"""

from __future__ import annotations

import contextlib
import threading

import jax

from ...framework import random as _random

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "determinate_seed"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_.clear()
        self.seeds_.clear()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already added")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already added")
        self.states_[name] = _random.StatefulKeySource(seed)

    def get_states_tracker(self):
        return {n: s.get_state() for n, s in self.states_.items()}

    def set_states_tracker(self, states):
        for n, v in states.items():
            if n in self.states_:
                self.states_[n].set_state(v)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} not added yet")
        with _random.key_source_guard(self.states_[name]):
            yield


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed if seed is not None else pyrandom.randint(0, 2 ** 31 - 1)
    from ..env import get_rank
    global_seed = seed
    local_seed = seed + 1024 + get_rank()
    _tracker.reset()
    _random.seed(global_seed)
    _tracker.add(MODEL_PARALLEL_RNG, local_seed)


def determinate_seed(name):
    return 0
