"""Elementwise + reduction math ops.

Parity target: `python/paddle/tensor/math.py` + `ops.py` (reference wraps
`_C_ops.*`; here every op's "kernel" is its jnp/lax lowering, registered in
ops/registry.py).

The elementwise corpus (unary/binary/comparisons) lives in the YAML single
source (`ops/specs/ops.yaml` -> `generated_ops.py`), matching the
reference's `phi/api/yaml/ops.yaml` pipeline; this module re-exports those
and keeps only the ops whose python wrappers need real logic (axis
normalization, Tensor-valued bounds, dtype plumbing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# YAML-generated single-source ops (registry name == public name, so AMP
# lists and SPMD bindings apply to them like any hand op)
from .generated_ops import (  # noqa: F401
    abs, acos, acosh, add, addmm, asin, asinh, atan, atan2, atanh, ceil,
    cos, cosh, deg2rad, digamma, divide, erf, erfinv, exp, expm1, float_power,
    floor, floor_divide, fmax, fmin, frac, gcd, heaviside, hypot, inner,
    isfinite, isinf, isnan, lcm, lerp, lgamma, log, log1p, log2, log10,
    maximum, minimum, mod, multiply, nan_to_num, neg, outer, pow, rad2deg,
    reciprocal, round, rsqrt, sign, sin, sinh, sqrt, square, stanh, subtract,
    tan, tanh, trace, trunc,
)
from .registry import dispatch as _d, register_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "scale", "neg", "abs", "sign", "sqrt", "rsqrt",
    "square", "reciprocal", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "floor", "ceil", "round", "trunc", "frac",
    "erf", "erfinv", "lgamma", "digamma", "clip", "maximum", "minimum",
    "fmax", "fmin", "atan2", "hypot", "logit", "nan_to_num",
    "sum", "mean", "max", "min", "prod", "logsumexp", "amax", "amin",
    "std", "var", "cumsum", "cumprod", "cummax", "cummin", "add_n",
    "isnan", "isinf", "isfinite", "nansum", "nanmean", "count_nonzero",
    "diff", "sgn", "trace", "inner", "outer", "heaviside", "rad2deg", "deg2rad",
    "lerp", "addmm", "increment", "stanh", "multiplex", "gcd", "lcm",
]

remainder = mod
sgn = sign

register_op("logit", jax.scipy.special.logit)


def logit(x, eps=None, name=None):
    if eps is not None:
        x = clip(x, eps, 1.0 - eps)
    return _d("logit", (x,), {})


register_op("scale", lambda x, *, scale, bias, bias_after_scale:
            x * scale + bias if bias_after_scale else (x + bias) * scale)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _d("scale", (x,), {"scale": float(scale), "bias": float(bias),
                             "bias_after_scale": bool(bias_after_scale)})
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


register_op("clip", lambda x, *, min, max: jnp.clip(x, min, max))


def clip(x, min=None, max=None, name=None):
    from ..framework.tensor import Tensor
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return _d("clip", (x,), {"min": mn, "max": mx})


# ---------------------------------------------------------------- reductions
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(op_name, jfn, has_dtype=False):
    if has_dtype:
        register_op(op_name, lambda x, *, axis, keepdim, dtype:
                    jfn(x, axis=axis, keepdims=keepdim, dtype=dtype))

        def fn(x, axis=None, dtype=None, keepdim=False, name=None, _op=op_name):
            from ..core.dtypes import convert_dtype
            return _d(_op, (x,), {"axis": _axis_arg(axis), "keepdim": bool(keepdim),
                                  "dtype": convert_dtype(dtype)})
    else:
        register_op(op_name, lambda x, *, axis, keepdim:
                    jfn(x, axis=axis, keepdims=keepdim))

        def fn(x, axis=None, keepdim=False, name=None, _op=op_name):
            return _d(_op, (x,), {"axis": _axis_arg(axis), "keepdim": bool(keepdim)})
    fn.__name__ = op_name
    return fn


sum = _reduce("sum", jnp.sum, has_dtype=True)  # noqa: A001
mean = _reduce("mean", jnp.mean)
max = _reduce("max", jnp.max)  # noqa: A001
min = _reduce("min", jnp.min)  # noqa: A001
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
prod = _reduce("prod", jnp.prod, has_dtype=True)
logsumexp = _reduce("logsumexp", lambda x, axis, keepdims:
                    jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
nansum = _reduce("nansum", jnp.nansum, has_dtype=True)
nanmean = _reduce("nanmean", jnp.nanmean)

register_op("std", lambda x, *, axis, unbiased, keepdim:
            jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))
register_op("var", lambda x, *, axis, unbiased, keepdim:
            jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _d("std", (x,), {"axis": _axis_arg(axis), "unbiased": bool(unbiased),
                            "keepdim": bool(keepdim)})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _d("var", (x,), {"axis": _axis_arg(axis), "unbiased": bool(unbiased),
                            "keepdim": bool(keepdim)})


register_op("count_nonzero", lambda x, *, axis, keepdim:
            jnp.count_nonzero(x, axis=axis, keepdims=keepdim))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _d("count_nonzero", (x,), {"axis": _axis_arg(axis), "keepdim": keepdim})


register_op("cumsum", lambda x, *, axis: jnp.cumsum(x, axis=axis))
register_op("cumprod", lambda x, *, axis: jnp.cumprod(x, axis=axis))


def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        from . import manipulation as _m
        x = _m.flatten(x)
        axis = 0
    out = _d("cumsum", (x,), {"axis": int(axis)})
    if dtype is not None:
        from . import manipulation as _m
        out = _m.cast(out, dtype)
    return out


def cumprod(x, dim=None, dtype=None, name=None):
    if dim is None:
        from . import manipulation as _m
        x = _m.flatten(x)
        dim = 0
    out = _d("cumprod", (x,), {"axis": int(dim)})
    if dtype is not None:
        from . import manipulation as _m
        out = _m.cast(out, dtype)
    return out


register_op("cummax_val", lambda x, *, axis: jax.lax.cummax(x, axis=axis))
register_op("cummin_val", lambda x, *, axis: jax.lax.cummin(x, axis=axis))


def cummax(x, axis=None, dtype="int64", name=None):
    axis = -1 if axis is None else int(axis)
    val = _d("cummax_val", (x,), {"axis": axis % x.ndim if axis < 0 else axis})
    return val, None  # indices path provided in search.cummax_with_indices


def cummin(x, axis=None, dtype="int64", name=None):
    axis = -1 if axis is None else int(axis)
    val = _d("cummin_val", (x,), {"axis": axis % x.ndim if axis < 0 else axis})
    return val, None


register_op("add_n", lambda xs: functools_reduce(xs))


def functools_reduce(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    return _d("add_n", (list(inputs),), {})


register_op("diff", lambda x, *, n, axis: jnp.diff(x, n=n, axis=axis))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _d("diff", (x,), {"n": n, "axis": axis})


def increment(x, value=1.0, name=None):
    x.set_value(x._value + value)
    return x


register_op("multiplex", lambda inputs, index:
            jnp.take_along_axis(jnp.stack(inputs, axis=0),
                                index.reshape(1, -1, 1).astype(jnp.int32),
                                axis=0)[0])


def multiplex(inputs, index, name=None):
    return _d("multiplex", (list(inputs), index), {})
