"""nn.Layer + layer zoo tests (mirrors test/legacy_test layer tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_layer_registries():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 3)
            self.w = paddle.Parameter(paddle.ones([2])._value)
            self.register_buffer("buf", paddle.zeros([1]))

        def forward(self, x):
            return self.fc(x)

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert "w" in names and "fc.weight" in names and "fc.bias" in names
    assert len(net.parameters()) == 3
    assert len(net.buffers()) == 1
    sd = net.state_dict()
    assert "buf" in sd and "fc.weight" in sd


def test_train_eval_propagates():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    net.train()
    assert net[1].training


def test_dropout_train_vs_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    out = d(x)
    assert 0 < float((out == 0).astype("float32").mean().item()) < 1
    d.eval()
    np.testing.assert_array_equal(d(x).numpy(), x.numpy())


def test_forward_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h1 = lin.register_forward_pre_hook(lambda l, inp: calls.append("pre"))
    h2 = lin.register_forward_post_hook(lambda l, inp, out: calls.append("post"))
    lin(paddle.ones([1, 2]))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()
    calls.clear()
    lin(paddle.ones([1, 2]))
    assert calls == []


def test_linear_matches_numpy():
    lin = nn.Linear(3, 2)
    x = np.random.rand(4, 3).astype(np.float32)
    ref = x @ lin.weight.numpy() + lin.bias.numpy()
    np.testing.assert_allclose(lin(paddle.to_tensor(x)).numpy(), ref, rtol=1e-5)


def test_conv2d_matches_reference_shapes():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    out = conv(paddle.randn([2, 3, 17, 17]))
    assert out.shape == [2, 8, 9, 9]
    g = nn.Conv2D(4, 8, 3, groups=2, padding=1)
    assert g(paddle.randn([1, 4, 8, 8])).shape == [1, 8, 8, 8]


def test_conv2d_grad_flows():
    conv = nn.Conv2D(1, 2, 3)
    out = conv(paddle.randn([1, 1, 5, 5]))
    out.sum().backward()
    assert conv.weight.grad is not None
    assert conv.bias.grad is not None


def test_conv_transpose_shape():
    convt = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1, output_padding=1)
    assert convt(paddle.randn([1, 4, 8, 8])).shape == [1, 2, 16, 16]


def test_batchnorm_stats_and_eval():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = paddle.randn([8, 3, 4, 4]) * 2 + 1
    out = bn(x)
    # normalized output ~ zero mean unit var per channel
    m = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
    assert bn._mean.numpy().any()
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [8, 3, 4, 4]


def test_layernorm_matches_numpy():
    ln = nn.LayerNorm(8)
    x = np.random.rand(2, 4, 8).astype(np.float32)
    out = ln(paddle.to_tensor(x)).numpy()
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_rmsnorm():
    rn = nn.RMSNorm(8)
    x = np.random.rand(2, 8).astype(np.float32)
    out = rn(paddle.to_tensor(x)).numpy()
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_groupnorm():
    gn = nn.GroupNorm(2, 4)
    out = gn(paddle.randn([2, 4, 3, 3]))
    assert out.shape == [2, 4, 3, 3]


def test_embedding_padding_idx_grad():
    emb = nn.Embedding(5, 3, padding_idx=0)
    out = emb(paddle.to_tensor([[0, 1]]))
    assert float(out[0, 0].abs().sum().item()) == 0.0
    out.sum().backward()
    assert emb.weight.grad is not None


def test_pools():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D((2, 2))(x).shape == [1, 2, 2, 2]
    x2 = np.random.rand(1, 1, 4, 4).astype(np.float32)
    out = nn.MaxPool2D(2)(paddle.to_tensor(x2)).numpy()
    ref = x2.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, ref)


def test_activations_shapes():
    x = paddle.randn([3, 3])
    for layer in [nn.ReLU(), nn.GELU(), nn.Sigmoid(), nn.Tanh(), nn.Silu(),
                  nn.LeakyReLU(), nn.ELU(), nn.Softmax(), nn.LogSoftmax(),
                  nn.Hardswish(), nn.Mish(), nn.SELU()]:
        assert layer(x).shape == [3, 3]


def test_softmax_values():
    x = np.array([[1.0, 2.0, 3.0]], np.float32)
    out = F.softmax(paddle.to_tensor(x)).numpy()
    e = np.exp(x - x.max())
    np.testing.assert_allclose(out, e / e.sum(), rtol=1e-5)


def test_sequential_and_layerlist():
    seq = nn.Sequential(("a", nn.Linear(2, 2)), ("b", nn.ReLU()))
    assert seq["a"] is seq[0]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll)) == 4


def test_mha_self_attention_causal_consistency():
    mha = nn.MultiHeadAttention(8, 2)
    x = paddle.randn([2, 4, 8])
    out = mha(x)
    assert out.shape == [2, 4, 8]
    out.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder_decoder():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 3, 16])
    out = model(src, tgt)
    assert out.shape == [2, 3, 16]


def test_lstm_gradients():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 6, 4])
    out, (h, c) = lstm(x)
    out.sum().backward()
    assert lstm.weight_ih_l0.grad is not None


def test_losses():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor([0, 1, 2, 3])
    l1 = nn.CrossEntropyLoss()(logits, labels)
    assert l1.shape == []
    # ignore_index
    labels2 = paddle.to_tensor([0, -100, 2, -100])
    l2 = nn.CrossEntropyLoss(ignore_index=-100)(logits, labels2)
    assert np.isfinite(l2.item())
    # soft label
    soft = F.softmax(paddle.randn([4, 5]))
    l3 = nn.CrossEntropyLoss(soft_label=True)(logits, soft)
    assert np.isfinite(l3.item())
    # label smoothing
    l4 = nn.CrossEntropyLoss(label_smoothing=0.1)(logits, labels)
    assert np.isfinite(l4.item())
    x, y = paddle.randn([3, 3]), paddle.randn([3, 3])
    assert nn.MSELoss()(x, y).shape == []
    assert nn.L1Loss()(x, y).shape == []
    p = F.sigmoid(x)
    t = (y > 0).astype("float32")
    assert np.isfinite(nn.BCELoss()(p, t).item())
    assert np.isfinite(nn.BCEWithLogitsLoss()(x, t).item())
    assert np.isfinite(nn.SmoothL1Loss()(x, y).item())


def test_cross_entropy_matches_numpy():
    logits = np.random.rand(6, 4).astype(np.float32)
    labels = np.array([0, 1, 2, 3, 0, 1])
    out = F.cross_entropy(paddle.to_tensor(logits),
                          paddle.to_tensor(labels)).item()
    e = np.exp(logits - logits.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    ref = -np.log(p[np.arange(6), labels]).mean()
    assert abs(out - ref) < 1e-5


def test_clip_grad_by_global_norm():
    p1 = paddle.Parameter(paddle.ones([2])._value)
    p2 = paddle.Parameter(paddle.ones([3])._value)
    g1 = paddle.full([2], 3.0)
    g2 = paddle.full([3], 4.0)
    clip = nn.ClipGradByGlobalNorm(1.0)
    out = clip([(p1, g1), (p2, g2)])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_state_dict_roundtrip_nested():
    m1 = nn.Sequential(nn.Linear(3, 4), nn.BatchNorm1D(4), nn.Linear(4, 2))
    x = paddle.randn([5, 3])
    m1.eval()
    ref = m1(x).numpy()
    m2 = nn.Sequential(nn.Linear(3, 4), nn.BatchNorm1D(4), nn.Linear(4, 2))
    m2.eval()
    missing, unexpected = m2.set_state_dict(m1.state_dict())
    assert not missing and not unexpected
    np.testing.assert_allclose(m2(x).numpy(), ref, rtol=1e-5)


def test_layer_to_dtype():
    lin = nn.Linear(2, 2)
    lin.bfloat16()
    assert str(lin.weight.dtype) == "bfloat16"
    lin.float()
    assert str(lin.weight.dtype) == "float32"


def test_initializers():
    from paddle_tpu.nn import initializer as I
    p = paddle.Parameter(paddle.zeros([100, 100])._value)
    I.XavierNormal()(p)
    std = p.numpy().std()
    assert 0.05 < std < 0.25
    I.Constant(3.0)(p)
    assert (p.numpy() == 3.0).all()
    I.Uniform(-0.5, 0.5)(p)
    assert -0.5 <= p.numpy().min() and p.numpy().max() <= 0.5
    I.Orthogonal()(p)
    q = p.numpy()
    np.testing.assert_allclose(q @ q.T, np.eye(100), atol=1e-4)


def test_fused_multi_head_attention_parity():
    """paddle.incubate.nn.functional.fused_multi_head_attention (ref
    fused_transformer.py:502): pre/post-LN fused self-attention block vs
    a manual composition; grads flow."""
    import numpy as np

    from paddle_tpu.incubate.nn import functional as IF

    rng = np.random.RandomState(0)
    B, S, H, nh = 2, 6, 16, 4
    hd = H // nh
    x = paddle.to_tensor(rng.randn(B, S, H).astype(np.float32))
    qkvw = paddle.to_tensor(rng.randn(3, nh, hd, H).astype(np.float32) * 0.2)
    qkvb = paddle.to_tensor(rng.randn(3, nh, hd).astype(np.float32) * 0.1)
    lw = paddle.to_tensor(rng.randn(H, H).astype(np.float32) * 0.2)
    lb = paddle.to_tensor(rng.randn(H).astype(np.float32) * 0.1)
    lns = paddle.to_tensor(np.ones(H, np.float32))
    lnb = paddle.to_tensor(np.zeros(H, np.float32))

    out = IF.fused_multi_head_attention(
        x, qkvw, lw, pre_layer_norm=False, ln_scale=lns, ln_bias=lnb,
        qkv_bias=qkvb, linear_bias=lb, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False)

    xn, qw, qb = (np.asarray(t._value) for t in (x, qkvw, qkvb))
    qkv = np.einsum("bsh,cndh->bscnd", xn, qw) + qb[None, None]
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    s = np.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bnqk,bnkd->bnqd", p, v).transpose(0, 2, 1, 3) \
        .reshape(B, S, H)
    o = o @ np.asarray(lw._value) + np.asarray(lb._value)
    o = xn + o
    mu = o.mean(-1, keepdims=True)
    var = o.var(-1, keepdims=True)
    want = (o - mu) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(out._value), want,
                               rtol=2e-4, atol=2e-5)

    # transpose_qkv_wb layout + attn_mask + grads
    qkvw2 = paddle.to_tensor(
        np.einsum("cndh->hcnd", qw).reshape(H, 3 * H).astype(np.float32))
    mask = paddle.to_tensor(
        np.where(np.tril(np.ones((1, 1, S, S))) > 0, 0.0, -1e9)
        .astype(np.float32))
    x2 = paddle.to_tensor(rng.randn(B, S, H).astype(np.float32))
    x2.stop_gradient = False
    out2 = IF.fused_multi_head_attention(
        x2, qkvw2, lw, pre_layer_norm=True, pre_ln_scale=lns,
        pre_ln_bias=lnb, attn_mask=mask, dropout_rate=0.0,
        attn_dropout_rate=0.0, num_heads=nh, transpose_qkv_wb=True)
    paddle.sum(out2 * out2).backward()
    assert x2.grad is not None
    assert np.isfinite(np.asarray(x2.grad._value)).all()


def test_incubate_fused_layers():
    """incubate.nn fused layer classes (ref incubate/nn/layer/
    fused_transformer.py + fused_dropout_add.py + fused_linear.py):
    shapes, training, dropout-mode semantics, ffn parity vs manual."""
    import numpy as np

    from paddle_tpu import optimizer
    from paddle_tpu.incubate import nn as inn
    from paddle_tpu.incubate.nn import functional as IF

    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 6, 16).astype(np.float32))

    enc = inn.FusedTransformerEncoderLayer(
        d_model=16, nhead=4, dim_feedforward=32, dropout_rate=0.0,
        normalize_before=True)
    assert tuple(enc(x).shape) == (2, 6, 16)
    opt = optimizer.Adam(learning_rate=1e-3, parameters=enc.parameters())
    tgt = paddle.to_tensor(rng.randn(2, 6, 16).astype(np.float32) * 0.1)
    l0 = None
    for _ in range(6):
        loss = paddle.mean((enc(x) - tgt) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        l0 = float(loss) if l0 is None else l0
    assert float(loss) < l0

    # fused_feedforward post-LN parity vs a manual composition
    w1 = paddle.to_tensor(rng.randn(16, 32).astype(np.float32) * 0.2)
    w2 = paddle.to_tensor(rng.randn(32, 16).astype(np.float32) * 0.2)
    lns = paddle.to_tensor(np.ones(16, np.float32))
    lnb = paddle.to_tensor(np.zeros(16, np.float32))
    got = IF.fused_feedforward(x, w1, w2, ln2_scale=lns, ln2_bias=lnb,
                               dropout1_rate=0.0, dropout2_rate=0.0,
                               training=False)
    xn = np.asarray(x._value)
    o = xn + np.maximum(xn @ np.asarray(w1._value), 0) \
        @ np.asarray(w2._value)
    mu = o.mean(-1, keepdims=True)
    want = (o - mu) / np.sqrt(o.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(got._value), want,
                               rtol=2e-4, atol=2e-5)

    # dropout-mode semantics at inference
    a = paddle.to_tensor(np.ones((4, 4), np.float32))
    b = paddle.to_tensor(np.zeros((4, 4), np.float32))
    r = IF.fused_dropout_add(a, b, p=0.25, training=False,
                             mode="downscale_in_infer")
    np.testing.assert_allclose(np.asarray(r._value), 0.75)
    np.testing.assert_allclose(
        np.asarray(IF.fused_dropout_add(a, b, p=0.25,
                                        training=False)._value), 1.0)

    assert tuple(inn.FusedLinear(16, 8)(x).shape) == (2, 6, 8)
    assert tuple(inn.FusedBiasDropoutResidualLayerNorm(
        16, dropout_rate=0.0)(x, x).shape) == (2, 6, 16)


def test_fused_multi_transformer_decode_parity():
    """FusedMultiTransformer (ref fused_transformer.py:994): stacked
    fused decoder with dense KV caches — one cached decode step equals
    the last position of the whole-sequence forward."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.incubate import nn as inn

    paddle.seed(0)
    B, S, H, nh, L = 2, 5, 16, 4, 2
    mt = inn.FusedMultiTransformer(H, nh, 32, num_layers=L,
                                   normalize_before=True)
    x = paddle.to_tensor(np.random.RandomState(0).randn(B, S, H)
                         .astype(np.float32))
    assert tuple(mt(x).shape) == (B, S, H)
    assert len(mt.parameters()) == 12 * L

    hd = H // nh
    caches = [jnp.zeros((2, B, nh, 16, hd), jnp.float32)
              for _ in range(L)]
    _, caches = mt(x, caches=caches)
    tok = paddle.to_tensor(np.random.RandomState(1).randn(B, 1, H)
                           .astype(np.float32))
    out_d, caches = mt(tok, caches=caches, time_step=S)
    want = mt(paddle.concat([x, tok], axis=1))
    np.testing.assert_allclose(np.asarray(out_d._value),
                               np.asarray(want._value)[:, -1:],
                               rtol=2e-4, atol=2e-5)


def test_fused_multi_transformer_grad_flow():
    """Regression (ADVICE r5 #2): the FFN activation used to run as a raw
    jax call wrapped back into a Tensor, detaching the tape — every
    parameter upstream of the activation (qkv/ln/ffn1) silently got no
    gradient while ffn2 still did.  All parameter groups must now
    receive nonzero grads through a training step."""
    import numpy as np

    from paddle_tpu.incubate import nn as inn

    paddle.seed(0)
    mt = inn.FusedMultiTransformer(16, 2, 32, num_layers=1,
                                   activation="gelu",
                                   normalize_before=True)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4, 16).astype(np.float32))
    loss = paddle.sum(mt(x) * mt(x))
    loss.backward()
    sd = dict(mt.named_parameters())
    for name in ("qkv_weight_0", "ln_scale_0", "ffn1_weight_0",
                 "ffn2_weight_0", "ffn_ln_scale_0", "linear_weight_0"):
        g = sd[name].grad
        assert g is not None, f"{name} got no gradient"
        assert float(np.abs(np.asarray(g._value)).max()) > 0, \
            f"{name} gradient is all-zero"
