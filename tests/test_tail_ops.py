"""Round-4 op-corpus tail (VERDICT missing list): linalg stragglers,
pooling-with-index, margin losses, deformable conv, detection heads.

Oracles: scipy/LAPACK for linalg, plain-conv equivalence for zero-offset
deformable conv, structural invariants for pooling/sampling ops.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.vision import ops as V

rng = np.random.RandomState(0)


def t(a):
    return paddle.to_tensor(np.asarray(a))


def test_matrix_exp():
    import scipy.linalg as sl
    m = rng.randn(3, 3).astype(np.float32)
    np.testing.assert_allclose(paddle.linalg.matrix_exp(t(m)).numpy(),
                               sl.expm(m), rtol=1e-4, atol=1e-4)


def test_ormqr_against_lapack():
    from scipy.linalg import lapack
    a = rng.randn(4, 3).astype(np.float32)
    lqr, tau, _, _ = lapack.sgeqrf(a)
    c = rng.randn(4, 2).astype(np.float32)
    for left, trans in ((True, False), (True, True)):
        want = lapack.sormqr("L", "T" if trans else "N", lqr, tau, c,
                            lwork=256)[0]
        got = paddle.linalg.ormqr(t(lqr), t(tau), t(c), left=left,
                                  transpose=trans).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    cr = rng.randn(2, 4).astype(np.float32)
    want = lapack.sormqr("R", "N", lqr, tau, cr, lwork=256)[0]
    got = paddle.linalg.ormqr(t(lqr), t(tau), t(cr), left=False).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_take_modes():
    x = rng.randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(
        paddle.take(t(x), t(np.array([0, 5, -1]))).numpy(),
        x.reshape(-1)[[0, 5, -1]])
    np.testing.assert_allclose(
        paddle.take(t(x), t(np.array([13, -14])), mode="wrap").numpy(),
        x.reshape(-1)[[1, 10]])
    np.testing.assert_allclose(
        paddle.take(t(x), t(np.array([100])), mode="clip").numpy(),
        x.reshape(-1)[[11]])


def test_as_strided_and_unfold():
    base = np.arange(12, dtype=np.float32)
    s = paddle.as_strided(t(base), shape=[3, 2], stride=[4, 1],
                          offset=1).numpy()
    np.testing.assert_allclose(
        s, np.lib.stride_tricks.as_strided(base[1:], (3, 2), (16, 4)))
    u = paddle.tensor_unfold(t(np.arange(10, dtype=np.float32)),
                             axis=0, size=4, step=2).numpy()
    assert u.shape == (4, 4)
    np.testing.assert_allclose(u[1], [2, 3, 4, 5])


def test_fill_diagonal_tensor_and_nanquantile():
    fd = paddle.fill_diagonal_tensor(
        t(np.zeros((3, 3), np.float32)),
        t(np.array([1., 2., 3.], np.float32))).numpy()
    np.testing.assert_allclose(np.diag(fd), [1, 2, 3])
    nq = paddle.nanquantile(t(np.array([1., np.nan, 3.], np.float32)),
                            q=0.5).numpy()
    np.testing.assert_allclose(nq, 2.0)


def test_max_pool_with_index_unpool_roundtrip():
    img = rng.randn(2, 3, 8, 8).astype(np.float32)
    out, idx = F.max_pool2d_with_index(t(img), kernel_size=2, stride=2)
    assert tuple(out.shape) == (2, 3, 4, 4)
    # indices address the flat H*W plane; scatter-back must place every
    # pooled max at its original position
    back = F.max_unpool2d(out, idx, kernel_size=2, stride=2)
    flat = back.numpy().reshape(2, 3, -1)
    onp = out.numpy().reshape(2, 3, -1)
    inp = img.reshape(2, 3, -1)
    iflat = idx.numpy().reshape(2, 3, -1)
    for n in range(2):
        for c in range(3):
            np.testing.assert_allclose(inp[n, c][iflat[n, c]], onp[n, c])
            np.testing.assert_allclose(flat[n, c][iflat[n, c]], onp[n, c])


def test_max_unpool3d_shape():
    x = rng.randn(1, 2, 2, 2, 2).astype(np.float32)
    idx = np.arange(16).reshape(1, 2, 2, 2, 2) % 64
    out = F.max_unpool3d(t(x), t(idx.astype(np.int32)), kernel_size=2,
                         stride=2)
    assert tuple(out.shape) == (1, 2, 4, 4, 4)


def test_fractional_pools():
    img = rng.randn(2, 3, 8, 8).astype(np.float32)
    fp = F.fractional_max_pool2d(t(img), output_size=3)
    assert tuple(fp.shape) == (2, 3, 3, 3)
    # each output cell is a max over a subset: bounded by the global max
    assert (fp.numpy() <= img.max(axis=(2, 3), keepdims=True) + 1e-6).all()
    fp3 = F.fractional_max_pool3d(
        t(rng.randn(1, 2, 6, 6, 6).astype(np.float32)), output_size=2)
    assert tuple(fp3.shape) == (1, 2, 2, 2, 2)


def test_class_center_sample():
    lab = np.array([0, 2, 1], np.int64)
    paddle.seed(3)
    rl, sampled = F.class_center_sample(t(lab), num_classes=10,
                                        num_samples=4)
    sn, rn = sampled.numpy(), rl.numpy()
    assert set(lab) <= set(sn)          # positives always kept
    assert len(set(sn.tolist())) == 4   # distinct classes
    for i in range(3):                  # labels remapped into sample space
        assert sn[rn[i]] == lab[i]


def test_margin_cross_entropy_reduces_to_softmax_ce():
    lab = np.array([0, 2, 1], np.int64)
    logits = np.clip(rng.randn(3, 5).astype(np.float32), -0.9, 0.9)
    # m1=1, m2=m3=0 -> plain scaled softmax CE
    l0 = F.margin_cross_entropy(t(logits), t(lab), margin1=1.0,
                                margin2=0.0, margin3=0.0, scale=1.0)
    import scipy.special as sp
    want = -np.take_along_axis(np.log(sp.softmax(logits, axis=1)),
                               lab[:, None], axis=1)
    np.testing.assert_allclose(l0.numpy(), want, rtol=1e-4, atol=1e-5)
    # a real margin must make the target strictly harder (loss up)
    lm = F.margin_cross_entropy(t(logits), t(lab), margin2=0.5, scale=1.0)
    assert (lm.numpy() >= l0.numpy() - 1e-6).all()


def test_hsigmoid_loss_trains_toward_labels():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.codegen_helpers import hsigmoid_loss
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, 10, (8,)))
    w = jnp.asarray(rng.randn(9, 6).astype(np.float32) * 0.1)

    def loss(w):
        return hsigmoid_loss(x, lab, w, None, num_classes=10).mean()

    l0 = float(loss(w))
    g = jax.grad(loss)(w)
    l1 = float(loss(w - 0.5 * g))
    assert l1 < l0  # differentiable and descending


def test_deformable_conv_zero_offset_equals_conv():
    import jax
    import jax.numpy as jnp
    dx = rng.randn(2, 4, 6, 6).astype(np.float32)
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    w = rng.randn(5, 4, 3, 3).astype(np.float32) * 0.1
    dc = V.deformable_conv(t(dx), t(off), t(w), padding=1)
    ref = jax.lax.conv_general_dilated(jnp.asarray(dx), jnp.asarray(w),
                                       (1, 1), [(1, 1), (1, 1)])
    np.testing.assert_allclose(dc.numpy(), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)
    # v2: all-ones mask is identity
    ones = np.ones((2, 9, 6, 6), np.float32)
    dc2 = V.deformable_conv(t(dx), t(off), t(w), mask=t(ones), padding=1)
    np.testing.assert_allclose(dc2.numpy(), dc.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_roi_and_psroi_pool():
    img = rng.randn(1, 3, 8, 8).astype(np.float32)
    boxes = np.array([[0., 0., 4., 4.], [2., 2., 7., 7.]], np.float32)
    rp = V.roi_pool(t(img), t(boxes), output_size=2).numpy()
    assert rp.shape == (2, 3, 2, 2)
    # whole-image ROI with 1x1 bins = global max
    whole = V.roi_pool(t(img), t(np.array([[0., 0., 7., 7.]], np.float32)),
                       output_size=1).numpy()
    np.testing.assert_allclose(whole[0, :, 0, 0], img[0].max(axis=(1, 2)),
                               rtol=1e-6)
    ps = V.psroi_pool(t(rng.randn(1, 8, 8, 8).astype(np.float32)),
                      t(boxes), output_size=2).numpy()
    assert ps.shape == (2, 2, 2, 2)


def test_prior_box_and_yolo():
    pb, pv = V.prior_box(t(rng.randn(1, 3, 4, 4).astype(np.float32)),
                         t(rng.randn(1, 3, 32, 32).astype(np.float32)),
                         min_sizes=[8.0], aspect_ratios=[2.0], clip=True)
    pbn = pb.numpy()
    assert pbn.shape[-1] == 4 and (pbn >= 0).all() and (pbn <= 1).all()
    yx = rng.randn(2, 3 * 9, 5, 5).astype(np.float32)
    yb, ys = V.yolo_box(t(yx), t(np.array([[64, 64], [32, 32]], np.int32)),
                        anchors=[10, 13, 16, 30, 33, 23], class_num=4)
    ybn = yb.numpy()
    assert ybn.shape == (2, 75, 4) and tuple(ys.shape) == (2, 75, 4)
    assert (ybn[..., 2] >= ybn[..., 0] - 1e-4).all()  # x2 >= x1
    gtb = (np.abs(rng.rand(2, 3, 4)) * 0.4 + 0.1).astype(np.float32)
    gtl = rng.randint(0, 4, (2, 3))
    yl = V.yolo_loss(t(yx), t(gtb), t(gtl),
                     anchors=[10, 13, 16, 30, 33, 23],
                     anchor_mask=[0, 1, 2], class_num=4)
    assert np.isfinite(yl.numpy()).all() and yl.shape[0] == 2


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small -> low level
                     [0, 0, 300, 300],    # large -> high level
                     [0, 0, 60, 60]], np.float32)
    multi, restore, _ = V.distribute_fpn_proposals(
        t(rois), 2, 5, 4, 224, rois_num=t(np.array([3], np.int32)))
    assert len(multi) == 4
    got = np.concatenate([m.numpy() for m in multi if m.numpy().size])
    back = got[restore.numpy().reshape(-1)]
    np.testing.assert_allclose(back, rois)


def test_nms_and_matrix_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = V.nms(t(boxes), 0.5, scores=t(scores)).numpy()
    assert list(keep) == [0, 2]  # box 1 suppressed by box 0
    bb = boxes[None]
    sc = np.array([[[0.0, 0.0, 0.0], scores]], np.float32)  # class 1 live
    out, nums = V.matrix_nms(t(bb), t(sc), score_threshold=0.1,
                             post_threshold=0.05, nms_top_k=10,
                             keep_top_k=10, background_label=0)
    o = out.numpy()
    assert o.shape[1] == 6 and nums.numpy()[0] == o.shape[0] >= 2
    assert (o[:, 0] == 1).all()  # class ids


def test_generate_proposals():
    rng2 = np.random.RandomState(1)
    N, A, H, W = 1, 3, 4, 4
    scores = rng2.rand(N, A, H, W).astype(np.float32)
    deltas = (rng2.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
    anchors = np.abs(rng2.rand(H, W, A, 4)).astype(np.float32)
    anchors[..., 2:] += anchors[..., :2] + 8.0
    var = np.ones((H, W, A, 4), np.float32)
    rois, rscores, n = V.generate_proposals(
        t(scores), t(deltas), t(np.array([[32, 32]], np.float32)),
        t(anchors), t(var), pre_nms_top_n=20, post_nms_top_n=5,
        return_rois_num=True)
    r = rois.numpy()
    assert r.shape[1] == 4 and r.shape[0] == int(n.numpy()[0]) <= 5
    assert (r[:, 0] <= r[:, 2] + 1e-5).all()
    assert (r >= -1e-5).all() and (r <= 32.0 + 1e-5).all()


def test_decode_jpeg_roundtrip():
    pytest.importorskip("PIL")
    import io
    from PIL import Image
    img = (rng.rand(8, 6, 3) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=95)
    data = np.frombuffer(buf.getvalue(), np.uint8)
    out = V.decode_jpeg(t(data)).numpy()
    assert out.shape == (3, 8, 6)
    assert np.abs(out.astype(int).mean() - img.mean()) < 20  # lossy


def test_mode_matches_scipy():
    from scipy import stats
    x = rng.randint(0, 4, (5, 9)).astype(np.float32)
    v, i = paddle.mode(t(x), axis=1)
    vn = v.numpy()
    # returned value's count must be maximal (scipy's count oracle)
    want_count = stats.mode(x, axis=1, keepdims=False).count
    got_count = (x == vn[:, None]).sum(axis=1)
    np.testing.assert_array_equal(got_count, want_count)
    # returned index must address an occurrence of the mode value
    np.testing.assert_allclose(
        np.take_along_axis(x, i.numpy()[:, None], axis=1)[:, 0], vn)
    # tie rule: the HIGHEST tied value wins (reference semantics)
    v2, _ = paddle.mode(t(np.array([[2., 2., 3., 3.]], np.float32)), axis=1)
    assert float(v2.numpy()[0]) == 3.0


def test_multiclass_nms():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                       [50, 50, 60, 60]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out, nums = V.multiclass_nms(t(boxes), t(scores), nms_threshold=0.5)
    o = out.numpy()
    assert nums.numpy()[0] == o.shape[0] == 2
    assert (o[:, 0] == 1).all() and o[0, 1] >= o[1, 1]
