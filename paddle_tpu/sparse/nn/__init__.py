"""paddle.sparse.nn — sparse layers.

Parity: `python/paddle/sparse/nn/` — layer/activation.py (ReLU, ReLU6,
LeakyReLU, Softmax), layer/conv.py (Conv3D `:252`, SubmConv3D `:375`,
Conv2D, SubmConv2D), layer/norm.py (BatchNorm `:28`), layer/pooling.py
(MaxPool3D).  Conv weights use the reference's sparse layout
(*kernel, Cin, Cout); all value math rides the dense autograd tape.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer.layers import Layer
from .. import unary as _unary
from ..creation import SparseCooTensor
from . import functional  # noqa: F401
from .functional import (conv2d, conv3d, max_pool3d, subm_conv2d,  # noqa: F401
                         subm_conv3d)

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv2D", "Conv3D",
           "SubmConv2D", "SubmConv3D", "BatchNorm", "MaxPool3D",
           "functional"]


class ReLU(Layer):
    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return _unary.relu(x)


class ReLU6(Layer):
    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return _unary.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return _unary.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis: int = -1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return _unary.softmax(x, self.axis)


class _SparseConvNd(Layer):
    _d = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        from ...ops.creation import create_parameter
        d = self._d
        ks = (kernel_size,) * d if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self._ks = ks
        fan_in = in_channels
        for k in ks:
            fan_in *= k
        import math as _pm
        bound = 1.0 / _pm.sqrt(fan_in)
        self.weight = create_parameter(
            list(ks) + [in_channels, out_channels], "float32")
        import numpy as _np
        rngw = _np.random.uniform(
            -bound, bound, tuple(ks) + (in_channels, out_channels))
        self.weight.set_value(jnp.asarray(rngw.astype(_np.float32)))
        if bias_attr is not False:
            self.bias = create_parameter([out_channels], "float32",
                                         is_bias=True)
        else:
            self.bias = None

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        fn = {(2, False): conv2d, (2, True): subm_conv2d,
              (3, False): conv3d, (3, True): subm_conv3d}[
                  (self._d, self._subm)]
        return fn(x, self.weight, self.bias, stride=self.stride,
                  padding=self.padding, dilation=self.dilation,
                  groups=self.groups)


class Conv3D(_SparseConvNd):
    """Parity: python/paddle/sparse/nn/layer/conv.py:252 Conv3D."""
    _d, _subm = 3, False


class SubmConv3D(_SparseConvNd):
    """Parity: python/paddle/sparse/nn/layer/conv.py:375 SubmConv3D."""
    _d, _subm = 3, True


class Conv2D(_SparseConvNd):
    _d, _subm = 2, False


class SubmConv2D(_SparseConvNd):
    _d, _subm = 2, True


class BatchNorm(Layer):
    """Sparse batch norm: per-channel statistics over the PRESENT values
    only (nnz rows), running stats for eval.  Parity:
    python/paddle/sparse/nn/layer/norm.py:28 BatchNorm (wraps the dense
    BN math over the value rows, as the reference does)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon)

    @property
    def weight(self):
        return self._bn.weight

    @property
    def bias(self):
        return self._bn.bias

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return x._replace(self._bn(x.values()))


class MaxPool3D(Layer):
    """Parity: python/paddle/sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return max_pool3d(x, self.kernel_size, self.stride, self.padding)
