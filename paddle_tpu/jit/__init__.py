from .api import StaticFunction, ignore_module, not_to_static, to_static  # noqa: F401
from .save_load import TranslatedLayer, load, save  # noqa: F401
from .sot import status  # noqa: F401  (capture/guard/break report)
