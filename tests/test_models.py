"""GPT / Llama model family tests (tiny configs, CPU mesh).

Parity model: the reference ecosystem's GPT/Llama pretraining tests
(`test/auto_parallel/hybrid_strategy/semi_auto_llama.py` and the fleet GPT
path of SURVEY.md §3.4): forward shape/loss sanity, backward reaches every
parameter, a jit-captured train step matches eager and learns, and TP
(mp=2) matches the dense model on the same weights.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import to_static
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _data(vocab, b=2, s=32, seed=0):
    rng = np.random.RandomState(seed)
    ids = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, vocab, (b, s)).astype("int32"))
    return ids, labels


@pytest.mark.parametrize("family,ctor,cfg_fn", [
    ("gpt", GPTForCausalLM, gpt3_tiny),
    ("llama", LlamaForCausalLM, llama_tiny),
])
def test_forward_backward_all_params(family, ctor, cfg_fn):
    paddle.seed(1)
    cfg = cfg_fn()
    model = ctor(cfg)
    ids, labels = _data(cfg.vocab_size)
    loss = model.compute_loss(ids, labels)
    # init loss ~ ln(vocab)
    assert 0.7 * np.log(cfg.vocab_size) < float(loss.item()) \
        < 1.4 * np.log(cfg.vocab_size)
    loss.backward()
    missing = [n for n, p in model.named_parameters() if p.grad is None]
    assert not missing, f"params with no grad: {missing}"


@pytest.mark.parametrize("ctor,cfg_fn", [
    (GPTForCausalLM, gpt3_tiny),
    # llama variant: 7s measured (PR 18 re-budget); the gpt param keeps the fast pin
    pytest.param(LlamaForCausalLM, llama_tiny, marks=pytest.mark.slow)])
def test_jit_train_step_matches_eager_and_learns(ctor, cfg_fn):
    def run(use_jit):
        paddle.seed(7)
        cfg = cfg_fn()
        model = ctor(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def train_step(ids, labels):
            loss = model.compute_loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        step = to_static(train_step) if use_jit else train_step
        ids, labels = _data(cfg.vocab_size, seed=3)
        return [float(step(ids, labels).item()) for _ in range(4)]

    eager = run(False)
    jitted = run(True)
    np.testing.assert_allclose(jitted, eager, rtol=2e-4, atol=2e-4)
    assert jitted[-1] < jitted[0]


def test_gpt_tp_matches_dense(hybrid_mesh):
    """mp=2 TP GPT == dense GPT on identical weights (fwd loss + grads)."""
    paddle.seed(5)
    dense = GPTForCausalLM(gpt3_tiny())
    tp = GPTForCausalLM(gpt3_tiny(tensor_parallel=True))
    tp.set_state_dict(dense.state_dict())
    ids, labels = _data(1024, seed=9)
    l_dense = dense.compute_loss(ids, labels)
    l_tp = tp.compute_loss(ids, labels)
    np.testing.assert_allclose(float(l_tp.item()), float(l_dense.item()),
                               rtol=1e-4)
    l_dense.backward()
    l_tp.backward()
    gd = dense.gpt.blocks[0].attn.qkv.weight.grad
    gt = tp.gpt.blocks[0].attn.qkv.weight.grad
    np.testing.assert_allclose(np.asarray(gt._value), np.asarray(gd._value),
                               rtol=1e-3, atol=1e-5)


def test_llama_tp_matches_dense(hybrid_mesh):
    paddle.seed(6)
    dense = LlamaForCausalLM(llama_tiny())
    tp = LlamaForCausalLM(llama_tiny(tensor_parallel=True))
    tp.set_state_dict(dense.state_dict())
    ids, labels = _data(1024, seed=10)
    np.testing.assert_allclose(float(tp.compute_loss(ids, labels).item()),
                               float(dense.compute_loss(ids, labels).item()),
                               rtol=1e-4)


def test_llama_gqa():
    paddle.seed(2)
    cfg = llama_tiny(num_kv_heads=2)
    model = LlamaForCausalLM(cfg)
    ids, labels = _data(cfg.vocab_size)
    loss = model.compute_loss(ids, labels)
    loss.backward()
    assert model.model.layers[0].self_attn.k_proj.weight.grad is not None
    # kv projections are half the size of q
    assert model.model.layers[0].self_attn.k_proj.weight.shape[1] == \
        model.model.layers[0].self_attn.q_proj.weight.shape[1] // 2


def test_gpt_kv_cache_attention():
    """Incremental decoding through the attention layer's kv cache matches
    the full-sequence forward (reference decode path:
    `fused_multi_transformer_op.cu.h` cache-KV branch)."""
    from paddle_tpu.models.gpt import GPTAttention
    paddle.seed(3)
    attn = GPTAttention(gpt3_tiny())
    attn.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(1, 8, 128).astype("float32"))
    full = attn(x)
    # prefill 7 tokens, then decode token 8 with the cache
    from paddle_tpu.ops import manipulation as _m
    prefix = paddle.to_tensor(np.asarray(x._value)[:, :7])
    _, cache = attn(prefix, kv_cache=(
        paddle.to_tensor(np.zeros((1, 0, 4, 32), np.float32)),
        paddle.to_tensor(np.zeros((1, 0, 4, 32), np.float32))))
    last = paddle.to_tensor(np.asarray(x._value)[:, 7:8])
    out_last, _ = attn(last, kv_cache=cache)
    np.testing.assert_allclose(np.asarray(out_last._value)[0, 0],
                               np.asarray(full._value)[0, 7],
                               rtol=1e-4, atol=1e-5)


def test_gpt_param_count():
    cfg = gpt3_tiny()
    model = GPTForCausalLM(cfg)
    n = model.num_params()
    H, L, V, S = (cfg.hidden_size, cfg.num_layers, cfg.vocab_size,
                  cfg.max_seq_len)
    expect = (V * H + S * H + 2 * H
              + L * (4 * H + H * 3 * H + 3 * H + H * H + H
                     + 2 * (H * 4 * H) + 4 * H + H))
    assert n == expect, (n, expect)


@pytest.mark.slow   # tier-1 budget (ISSUE 9): heavy, not on the serving/training core path
def test_gpt_moe_trains_and_ep_shards():
    """GPT-MoE: alternating MoE blocks train under jit; expert weights
    shard over an ep mesh axis with identical eval outputs."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, moe_num_experts=4,
                    moe_every_n_layers=2, moe_top_k=1)
    model = GPTForCausalLM(cfg)
    assert isinstance(model.gpt.blocks[1].mlp, MoELayer)
    assert not isinstance(model.gpt.blocks[0].mlp, MoELayer)

    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    from paddle_tpu.jit import to_static

    def train_step(ids, labels):
        loss = model.compute_loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = to_static(train_step)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (4, 16)).astype(np.int32))
    losses = [float(step(ids, ids)._value) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    # expert grads flowed
    moe = model.gpt.blocks[1].mlp
    assert np.abs(np.asarray(moe.experts.w1._value)).sum() > 0

    # EP sharding parity on eval
    model.eval()
    want = np.asarray(model(ids)._value)
    mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
    # the donated train step left every param committed to one device;
    # an SPMD eval needs the WHOLE model on the mesh: replicate
    # non-expert params, shard expert stacks over ep (what shard_layer
    # does for users)
    for prm in model.parameters():
        prm._value = jax.device_put(prm._value, NamedSharding(mesh, P()))
    for pname in ("w1", "b1", "w2", "b2"):
        prm = getattr(moe.experts, pname)
        prm._value = jax.device_put(prm._value,
                                    NamedSharding(mesh, P("ep")))
    got = np.asarray(to_static(lambda t: model(t))(ids)._value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-5)


@pytest.mark.slow  # 8s measured: MoE + recompute composition; plain GPT jit-train parity and test_moe dispatch parity stay fast
def test_gpt_moe_with_recompute_trains():
    """Aux loss + remat: MoE blocks skip the checkpoint, training works."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    max_seq_len=32, moe_num_experts=2, moe_top_k=1,
                    use_recompute=True)
    model = GPTForCausalLM(cfg)
    ids = paddle.to_tensor((np.arange(32) % 64).reshape(2, 16)
                           .astype(np.int32))
    loss = model.compute_loss(ids, ids)
    loss.backward()
    assert np.isfinite(float(loss._value))
    # top-1 maps to SwitchGate: aux loss is live (nonzero)
    aux = model.gpt.blocks[1].mlp.l_aux
    assert float(np.asarray(aux._value)) > 0
    with pytest.raises(ValueError):
        GPTConfig(moe_num_experts=2, moe_every_n_layers=0)


def test_gpt_selective_recompute_parity():
    """recompute_interval and recompute_policy change only memory/FLOPs,
    never the math: identical loss + grads vs no-remat."""
    import numpy as np
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 1024, (2, 32)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, 1024, (2, 32)).astype(np.int32))

    losses, grads = [], []
    for kw in (dict(use_recompute=False),
               dict(use_recompute=True),
               dict(use_recompute=True, recompute_interval=2),
               dict(use_recompute=True,
                    recompute_policy="dots_with_no_batch_dims_saveable")):
        paddle.seed(7)
        m = GPTForCausalLM(gpt3_tiny(num_layers=4, **kw))
        m.train()
        loss = m.compute_loss(ids, labels)
        loss.backward()
        losses.append(float(loss))
        grads.append(np.asarray(m.gpt.blocks[0].attn.qkv.weight.grad._value))
    for l in losses[1:]:
        np.testing.assert_allclose(l, losses[0], rtol=1e-6)
    for g in grads[1:]:
        np.testing.assert_allclose(g, grads[0], rtol=2e-5, atol=2e-6)
