"""ShuffleNetV2. Parity: `python/paddle/vision/models/shufflenetv2.py`.

Channel shuffle is a reshape-transpose-reshape — free layout work for XLA.
"""

from __future__ import annotations

from ... import nn
from ...ops import manipulation as _m

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


def _channel_shuffle(x, groups: int):
    n, c, h, w = x.shape
    x = _m.reshape(x, [n, groups, c // groups, h, w])
    x = _m.transpose(x, perm=[0, 2, 1, 3, 4])
    return _m.reshape(x, [n, c, h, w])


def _conv_bn(inp, oup, k, stride, groups=1, act="relu"):
    layers = [nn.Conv2D(inp, oup, k, stride, (k - 1) // 2, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(oup)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "swish":
        layers.append(nn.Swish())
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_features = oup // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(inp // 2, branch_features, 1, 1, act=act),
                _conv_bn(branch_features, branch_features, 3, 1,
                         groups=branch_features, act="none"),
                _conv_bn(branch_features, branch_features, 1, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(inp, inp, 3, stride, groups=inp, act="none"),
                _conv_bn(inp, branch_features, 1, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn(inp, branch_features, 1, 1, act=act),
                _conv_bn(branch_features, branch_features, 3, stride,
                         groups=branch_features, act="none"),
                _conv_bn(branch_features, branch_features, 1, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = _m.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = _m.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"supported scales: {sorted(_STAGE_OUT)}")
        outs = _STAGE_OUT[scale]
        self.conv1 = _conv_bn(3, outs[0], 3, 2, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = outs[0]
        for idx, repeat in enumerate(_REPEATS):
            oup = outs[idx + 1]
            blocks = [_InvertedResidual(inp, oup, 2, act)]
            for _ in range(repeat - 1):
                blocks.append(_InvertedResidual(oup, oup, 1, act))
            stages.append(nn.Sequential(*blocks))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(inp, outs[4], 1, 1, act=act)
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(outs[4], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(_m.flatten(x, start_axis=1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(1.0, act="swish", **kw)
