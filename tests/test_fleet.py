"""Replica fleet (ISSUE 16): prefix-affinity router, predicted-TTFT
shedding, failover, disaggregated prefill/decode handoff, and the
zero-downtime rolling-restart drill.

Fast layer — STUB replicas (tiny canned-HTTP servers, no engine, no
compile): the routing decision (`plan`), rendezvous stability,
queue-position TTFT prediction, shed/failover/unroutable status codes,
byte-faithful SSE passthrough, and the `fleet.proxy.connect` chaos
site.  Real-engine layer — the cross-engine KV handoff bit-match
(satellite 3, fast: two tiny engines) and the @slow 3-replica drills:
affinity hit-rate > 0.9 under shared-prefix traffic and the
chaos-tested rolling restart with ZERO dropped requests.
"""

import json
import socket
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.fleet import (DisaggregatedPair, Fleet,
                                        FleetRouter, Replica,
                                        affinity_key, hand_off,
                                        predict_ttft_s)
from paddle_tpu.inference.fleet.router import rendezvous_order
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.testing import chaos


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_context", 64)
    kw.setdefault("block_size", 16)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(model, **kw)


# ================================================== stub replica layer

READY_DOC = {"ready": True, "running": 0, "waiting": 0, "queue_depth": 0,
             "slots": 2, "free_slots": 2, "prefilling": 0,
             "ttft_evidence": {"admit_rate_per_s": 0.0,
                               "ttft_p50_s": 0.0, "samples": 0}}

SSE_PAYLOAD = (b'data: {"token": 7, "n": 0}\n\n'
               b': ping\n\n'
               b'data: {"token": 9, "n": 1}\n\n'
               b'event: done\n'
               b'data: {"rid": 1, "outcome": "finished", '
               b'"output_ids": [7, 9]}\n\n')


class _StubHandler(BaseHTTPRequestHandler):
    def log_message(self, format, *args):  # noqa: A002
        pass

    def _reply(self, code, ctype, body):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        doc = self.server.doc
        self._reply(200 if doc.get("ready") else 503,
                    "application/json", json.dumps(doc).encode())

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length") or 0)
        self.server.bodies.append(self.rfile.read(n))
        if self.server.generate_status != 200:
            self._reply(self.server.generate_status, "application/json",
                        b'{"error": "draining"}')
            return
        self._reply(200, "text/event-stream", self.server.sse_payload)


class _Stub:
    """A canned engine-replica frontend: /healthz from a settable doc,
    /generate records the body and replays a fixed SSE byte stream."""

    def __init__(self, doc=None, generate_status=200, sse=SSE_PAYLOAD):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
        self._httpd.daemon_threads = True
        self._httpd.doc = dict(doc or READY_DOC)
        self._httpd.generate_status = generate_status
        self._httpd.sse_payload = sse
        self._httpd.bodies = []
        self.port = self._httpd.server_address[1]
        self._t = threading.Thread(target=self._httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    @property
    def addr(self):
        return f"127.0.0.1:{self.port}"

    @property
    def bodies(self):
        return self._httpd.bodies

    def set_doc(self, **kw):
        self._httpd.doc.update(kw)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._t.join(timeout=5)


def _post_generate(port, prompt_ids, timeout=30, **kw):
    """POST /generate, drain the response; returns (status, body bytes)."""
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt_ids":
                                      [int(t) for t in prompt_ids],
                                      **kw}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _sse_outcome(body_bytes):
    """The terminal (event, payload) of an SSE byte stream."""
    event, last = None, (None, None)
    for raw in body_bytes.split(b"\n"):
        line = raw.decode()
        if line.startswith("event: "):
            event = line[7:]
        elif line.startswith("data: "):
            last = (event, json.loads(line[6:]))
            event = None
    return last


def _prompt_homed_at(router, name, length=8):
    """A prompt whose rendezvous home is replica ``name``."""
    for s in range(1, 500):
        ids = [s] * length
        if router.plan(ids)["home"] == name:
            return ids
    raise AssertionError(f"no prompt homed at {name}")


# ------------------------------------------------ affinity / prediction

def test_affinity_key_shares_prefix_window():
    a = affinity_key([1, 2, 3, 4, 99], affinity_tokens=4)
    b = affinity_key([1, 2, 3, 4, 7, 7], affinity_tokens=4)
    c = affinity_key([1, 2, 3, 5], affinity_tokens=4)
    assert a == b and a != c
    # the window is the routing granularity: beyond it nothing matters
    assert affinity_key([1, 2], affinity_tokens=2) == \
        affinity_key([1, 2, 500], affinity_tokens=2)


def test_rendezvous_membership_change_moves_only_the_leavers_keys():
    names = ["r0", "r1", "r2"]
    keys = [affinity_key([i, i + 1, i + 2], affinity_tokens=3)
            for i in range(100)]
    before = {k: rendezvous_order(k, names)[0] for k in keys}
    after = {k: rendezvous_order(k, ["r0", "r2"])[0] for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # every moved key belonged to the leaver; everyone else is stable
    assert all(before[k] == "r1" for k in moved)
    assert {before[k] for k in keys} == {"r0", "r1", "r2"}


def test_predict_ttft_queue_position_model():
    assert predict_ttft_s({}) == 0.0    # cold replica never starves
    ev = {"ttft_p50_s": 0.5, "admit_rate_per_s": 2.0}
    # empty queue, free slot: just the base TTFT
    assert predict_ttft_s({"waiting": 0, "free_slots": 1,
                           "ttft_evidence": ev}) == pytest.approx(0.5)
    # 3 queued at 2 admissions/s -> 1.5s wait + base
    assert predict_ttft_s({"waiting": 3, "free_slots": 1,
                           "ttft_evidence": ev}) == pytest.approx(2.0)
    # no free slot costs one more queue position
    assert predict_ttft_s({"waiting": 3, "free_slots": 0,
                           "ttft_evidence": ev}) == pytest.approx(2.5)
    # no rate evidence: each position costed at one base TTFT
    assert predict_ttft_s(
        {"waiting": 2, "free_slots": 1,
         "ttft_evidence": {"ttft_p50_s": 0.5}}) == pytest.approx(1.5)


# --------------------------------------------------- routing via stubs

def test_router_affinity_home_and_sse_passthrough():
    stubs = [_Stub() for _ in range(3)]
    router = FleetRouter({f"r{i}": s.addr for i, s in enumerate(stubs)},
                         port=0, poll_interval_s=30.0)
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        plan = router.plan(prompt)
        home_stub = stubs[int(plan["home"][1:])]
        for _ in range(5):
            status, body = _post_generate(router.port, prompt)
            assert status == 200
            assert body == SSE_PAYLOAD   # byte-faithful SSE passthrough
        assert len(home_stub.bodies) == 5
        sent = json.loads(home_stub.bodies[0])
        assert sent["prompt_ids"] == prompt
        st = router.stats()
        assert st["routed"] == 5 and st["affinity_hit_rate"] == 1.0
        assert st["per_replica"][plan["home"]] == 5
    finally:
        router.close()
        for s in stubs:
            s.close()


def test_router_sheds_by_predicted_ttft():
    busy = dict(READY_DOC, waiting=8, free_slots=0,
                ttft_evidence={"admit_rate_per_s": 1.0,
                               "ttft_p50_s": 1.0, "samples": 32})
    stubs = [_Stub(doc=busy) for _ in range(2)]
    router = FleetRouter({f"r{i}": s.addr for i, s in enumerate(stubs)},
                         port=0, ttft_budget_ms=500.0,
                         poll_interval_s=30.0)
    try:
        plan = router.plan([1, 2, 3])
        assert plan["shed"] and plan["order"] == []
        status, body = _post_generate(router.port, [1, 2, 3])
        assert status == 429
        doc = json.loads(body)
        assert doc["reason"] == "predicted_ttft"
        assert set(doc["predicted_ttft_ms"]) == {"r0", "r1"}
        assert all(v > 500.0 for v in doc["predicted_ttft_ms"].values())
        assert router.stats()["sheds"] == 1
        # one replica clears its queue (and its recent TTFT comes back
        # under budget) -> routable again
        stubs[0].set_doc(waiting=0, free_slots=2,
                         ttft_evidence={"admit_rate_per_s": 1.0,
                                        "ttft_p50_s": 0.2,
                                        "samples": 32})
        router.poll_once("r0")
        status, body = _post_generate(router.port, [1, 2, 3])
        assert status == 200 and body == SSE_PAYLOAD
    finally:
        router.close()
        for s in stubs:
            s.close()


def test_router_fails_over_on_draining_503():
    live = _Stub()
    draining = _Stub(generate_status=503)
    router = FleetRouter({"live": live.addr, "drn": draining.addr},
                         port=0, poll_interval_s=30.0)
    try:
        prompt = _prompt_homed_at(router, "drn")
        status, body = _post_generate(router.port, prompt)
        assert status == 200 and body == SSE_PAYLOAD
        st = router.stats()
        assert st["failovers"] == 1 and st["fallbacks"] == 1
        assert st["per_replica"]["live"] == 1
        # the 503 marked the replica down inline (no poll-tick wait)
        assert router.describe()["replicas"]["drn"]["ready"] is False
    finally:
        router.close()
        live.close()
        draining.close()


def test_router_fails_over_on_chaos_connect_fault():
    stubs = [_Stub() for _ in range(2)]
    router = FleetRouter({f"r{i}": s.addr for i, s in enumerate(stubs)},
                         port=0, poll_interval_s=30.0)
    try:
        with chaos.fail_at("fleet.proxy.connect", on_calls=[1]) as fault:
            status, body = _post_generate(router.port, [1, 2, 3, 4])
        assert fault.fires == 1
        assert status == 200 and body == SSE_PAYLOAD
        assert router.stats()["failovers"] == 1
    finally:
        router.close()
        for s in stubs:
            s.close()


def test_router_replays_stream_that_dies_before_first_token():
    """ISSUE 20 satellite: an accepted stream that terminates before
    the FIRST token frame — a terminal ``event: error`` opening frame,
    or upstream EOF before any complete frame — is replayed on the next
    replica: zero bytes reached the client, so the re-route is
    idempotent and the client sees one clean stream.  A stream that
    dies AFTER delivering a token is NOT replayed (the truncation must
    surface; a replay would duplicate tokens)."""
    err_first = b'event: error\ndata: {"error": "oom"}\n\n'
    bad = _Stub(sse=err_first)
    dead = _Stub(sse=b"")           # 200 + EOF before any frame
    ok = _Stub()
    router = FleetRouter({"bad": bad.addr, "dead": dead.addr,
                          "ok": ok.addr}, port=0, poll_interval_s=30.0)
    try:
        prompt = _prompt_homed_at(router, "bad")
        status, body = _post_generate(router.port, prompt)
        assert status == 200 and body == SSE_PAYLOAD
        assert _sse_outcome(body)[0] == "done"
        st = router.stats()
        assert st["replayed"] >= 1
        assert st["per_replica"]["ok"] == 1
    finally:
        router.close()
        for s in (bad, dead, ok):
            s.close()

    trunc_payload = b'data: {"token": 7, "n": 0}\n\n'
    trunc = _Stub(sse=trunc_payload)
    spare = _Stub()
    router = FleetRouter({"trunc": trunc.addr, "spare": spare.addr},
                         port=0, poll_interval_s=30.0)
    try:
        prompt = _prompt_homed_at(router, "trunc")
        status, body = _post_generate(router.port, prompt)
        # the token frame was delivered, then the stream ended: the
        # truncation reaches the client as-is, with no replay
        assert status == 200 and body == trunc_payload
        assert router.stats()["replayed"] == 0
    finally:
        router.close()
        trunc.close()
        spare.close()


def test_router_dead_replica_routed_around_and_endpoints():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    live = _Stub()
    router = FleetRouter({"dead": dead_addr, "live": live.addr},
                         port=0, poll_interval_s=30.0,
                         retry_window_s=0.2)
    try:
        # construction-time poll already marked it down
        fleet_doc = router.describe()
        assert fleet_doc["replicas"]["dead"]["ready"] is False
        assert fleet_doc["replicas"]["dead"]["last_err"]
        status, body = _post_generate(router.port, [9, 9, 9])
        assert status == 200 and body == SSE_PAYLOAD
        # router's own healthz: ready while anyone is
        conn = HTTPConnection("127.0.0.1", router.port, timeout=5)
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
        conn.close()
        router.cordon("live")
        conn = HTTPConnection("127.0.0.1", router.port, timeout=5)
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 503
        conn.close()
        # nothing routable -> 503, counted
        status, body = _post_generate(router.port, [9, 9, 9])
        assert status == 503 and router.stats()["unroutable"] == 1
        router.uncordon("live")
        # malformed body -> 400 at the router, nothing proxied
        conn = HTTPConnection("127.0.0.1", router.port, timeout=5)
        conn.request("POST", "/generate", body="{}",
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
    finally:
        router.close()
        live.close()


# ------------------------------- cross-engine KV handoff (satellite 3)

def test_cross_engine_handoff_streams_bit_match(model, tmp_path):
    """Disaggregated prefill/decode: engine A prefills + exports, engine
    B adopts the bundle and decodes — the stream bit-matches the
    single-engine run, B's prefill is a prefix HIT over adopted KV, and
    the refcount transfer leaves A's pool clean (blocksan-checked
    inside hand_off on both sides)."""
    prompt = list(np.random.RandomState(5).randint(1, 1000, (21,)))
    ref_eng = _engine(model)
    ref = ref_eng.add_request(Request(prompt, max_new_tokens=6))
    ref_eng.run()
    assert len(ref.output_ids) == 6

    pair = DisaggregatedPair(_engine(model), _engine(model),
                             str(tmp_path / "handoff"))
    out = pair.generate(prompt, max_new_tokens=6)
    assert out == list(ref.output_ids)

    rep = pair.last_report
    assert rep["exported"]["entries"] >= 1
    assert rep["released_blocks"] == rep["exported"]["blocks"] > 0
    assert rep["imported"]["blocks"] == rep["exported"]["blocks"]
    # decode side admitted THROUGH the adopted prefix
    assert pair.decode.stats()["prefix_cache"]["hits"] >= 1
    # ownership transferred: the prefill engine's pool is all-free again
    a = pair.prefill.stats()
    assert a["free_blocks"] == pair.prefill.num_blocks
    # a second handoff round-trips the other direction's state too
    out2 = pair.generate(prompt, max_new_tokens=6)
    assert out2 == out


def test_hand_off_between_fresh_engines(model, tmp_path):
    """Bare hand_off: exported entries re-pinned in the destination's
    ledger (rc transfer), importable into a THIRD engine from the same
    bundle root (newest version wins)."""
    src = _engine(model)
    r = src.add_request(Request(list(range(1, 18)), max_new_tokens=2))
    src.run()
    assert len(r.output_ids) == 2
    dst = _engine(model)
    report = hand_off(src, dst, str(tmp_path / "root"))
    assert report["imported"]["blocks"] == report["exported"]["blocks"]
    assert src.stats()["free_blocks"] == src.num_blocks
    # the adopted prefix serves a suffix-only admission on dst
    r2 = dst.add_request(Request(list(range(1, 18)), max_new_tokens=2))
    dst.run()
    assert list(r2.output_ids) == list(r.output_ids)
    assert dst.stats()["prefix_cache"]["hits"] >= 1


# ================================================= real-engine drills

def _fleet(tmp_path, n=3, **router_kw):
    def factory(export_dir):
        # CONCURRENT replicas must not share a model object: engine
        # traces bind parameter values into the model's Parameters, so
        # two engines tracing at once leak tracers into each other.
        # Same seed -> identical weights (and export fingerprints), own
        # copy per replica — like a real fleet.
        paddle.seed(0)
        m = GPTForCausalLM(gpt3_tiny())
        m.eval()
        # a roomy block pool: the drills measure routing + lifecycle,
        # not eviction pressure (pressure would churn prefix entries
        # and turn the affinity-value assertion into a pool-size test)
        return _engine(m, prefix_export_dir=export_dir, num_blocks=32)
    router_kw.setdefault("poll_interval_s", 0.1)
    # the affinity window must match the SHARED span of the traffic
    # (here: 16-token system prompts = one engine block); wider and
    # every request hashes its unique tail into the key, scattering
    # same-prefix traffic across homes
    router_kw.setdefault("affinity_tokens", 16)
    return Fleet.build(factory, n, str(tmp_path / "fleet"), **router_kw)


@pytest.mark.slow   # 3 engines warm up; the stub tests pin the routing
def test_fleet_affinity_hit_rate_gate(tmp_path):
    """Shared-prefix traffic through a healthy 3-replica fleet lands on
    its rendezvous home essentially always (acceptance gate: > 0.9) —
    and that affinity is WORTH something: the home replicas' prefix
    caches serve hits."""
    fleet = _fleet(tmp_path)
    try:
        rng = np.random.RandomState(7)
        prefixes = [list(rng.randint(1, 1000, (16,))) for _ in range(4)]
        # warm wave: one request per prefix, sequential, so each home
        # replica REGISTERS the prefix blocks before the storm (two
        # same-prefix admissions racing the first registration both
        # miss — that's admission pipelining, not an affinity failure)
        for p in prefixes:
            status, body = _post_generate(fleet.router.port, p + [1],
                                          max_new_tokens=2)
            assert status == 200 and _sse_outcome(body)[0] == "done"
        jobs = [(p + [int(t)]) for p in prefixes
                for t in rng.randint(2, 1000, (5,))]
        results = []

        def client(ids):
            results.append(_post_generate(fleet.router.port, ids,
                                          max_new_tokens=3))

        threads = [threading.Thread(target=client, args=(j,))
                   for j in jobs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == len(jobs)
        assert all(status == 200 for status, _ in results)
        assert all(_sse_outcome(body)[0] == "done"
                   for _, body in results)
        st = fleet.router.stats()
        assert st["routed"] == len(jobs) + len(prefixes)
        assert st["affinity_hit_rate"] > 0.9
        # affinity is worth something: the storm admits through the
        # warmed home caches (a small slack for affinity fallbacks)
        hits = sum(r.engine.stats()["prefix_cache"]["hits"]
                   for r in fleet.replicas)
        assert hits >= len(jobs) - 2
    finally:
        fleet.close()


@pytest.mark.slow   # the chaos drill: full restarts under live traffic
def test_rolling_restart_drops_zero_requests(tmp_path):
    """The acceptance gate: a rolling restart of all 3 replicas under
    continuous shared-prefix traffic — with a chaos connect fault
    injected at the router's proxy leg mid-drill — completes every
    single request (every stream ends `event: done`, no 4xx/5xx), while
    each replica really did restart and warm-import its exported
    prefix KV."""
    fleet = _fleet(tmp_path)
    try:
        rng = np.random.RandomState(11)
        prefixes = [list(rng.randint(1, 1000, (16,))) for _ in range(3)]
        stop = threading.Event()
        results, errors = [], []

        def client(k):
            i = 0
            while not stop.is_set():
                ids = prefixes[(k + i) % 3] + [i % 997 + 1]
                try:
                    results.append(_post_generate(
                        fleet.router.port, ids, max_new_tokens=2))
                except Exception as e:  # noqa: BLE001 - gate counts all
                    errors.append(repr(e))
                i += 1

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)          # steady traffic before the drill
        with chaos.fail_at("fleet.proxy.connect",
                           on_calls=[2, 5]) as fault:
            report = fleet.rolling_restart()
        time.sleep(0.5)          # and after it
        stop.set()
        for t in threads:
            t.join(timeout=120)

        assert errors == []
        assert len(results) > 0
        bad = [(status, _sse_outcome(body))
               for status, body in results
               if status != 200 or _sse_outcome(body)[0] != "done"]
        assert bad == []         # ZERO dropped requests
        assert fault.fires >= 1  # the chaos fault really fired...
        # ...and every fired fault was absorbed by a failover
        assert fleet.router.stats()["failovers"] >= fault.fires
        assert set(report["replicas"]) == {"r0", "r1", "r2"}
        adopted = 0
        for rep in fleet.replicas:
            assert rep.restarts == 1
            info = rep.engine._prefix_import_info
            assert info is not None        # every replica warm-imported
            adopted += info.get("blocks", 0)
        # the fleet as a whole carried KV across the restarts (one
        # replica may legitimately export nothing if rendezvous homed
        # no prefix on it)
        assert adopted >= 1
    finally:
        fleet.close()


@pytest.mark.slow   # replica lifecycle against a real engine
def test_replica_restart_keeps_port_and_warms_from_export(model,
                                                          tmp_path):
    rep = Replica("r0", lambda: _engine(
        model, prefix_export_dir=str(tmp_path / "r0")))
    try:
        rep.start()
        port0 = rep.server.port
        first = rep.engine
        status, body = _post_generate(port0, list(range(1, 18)),
                                      max_new_tokens=2)
        assert status == 200 and _sse_outcome(body)[0] == "done"
        report = rep.restart()
        assert rep.server.port == port0          # same front door
        assert rep.engine is not first           # genuinely new engine
        assert report["drain"]["export"]["entries"] >= 1
        assert report["import"]["blocks"] >= 1
        # the warmed cache answers without refilling: prefix hit
        status, body2 = _post_generate(port0, list(range(1, 18)),
                                       max_new_tokens=2)
        assert status == 200
        assert _sse_outcome(body2)[1]["output_ids"] == \
            _sse_outcome(body)[1]["output_ids"]
        assert rep.engine.stats()["prefix_cache"]["hits"] >= 1
    finally:
        rep.stop()
