"""Op registry + eager dispatcher.

TPU-native analogue of the reference's PHI kernel registry/dispatch pipeline
(`paddle/phi/core/kernel_registry.h:196` PD_REGISTER_KERNEL,
`phi/core/kernel_factory.h:326` SelectKernelOrThrowError, and the generated
``*_ad_func`` eager functions from `fluid/eager/auto_code_generator/generator/
eager_gen.py`; exemplar `multiply_fwd_func.cc:39`).

Design: every op is a pure function over raw jax values.  Dispatch does, in
the same order as the reference's generated ad_func:
  1. AMP autocast (hook installed by paddle_tpu.amp; ref `multiply_fwd_func.cc:54`)
  2. forward — under grad, via ``jax.vjp`` so XLA keeps the residuals
     (replacing TensorWrapper saves) unless the op registered a manual VJP
  3. NaN/Inf scan when FLAGS_check_nan_inf (ref `multiply_fwd_func.cc:140`)
  4. GradNode creation + edge wiring (ref `multiply_fwd_func.cc:164-192`)

"Kernel selection" is XLA's job: each op's forward is its lowering rule to
StableHLO; per-shape executable caching is handled by JAX's op-by-op jit
cache.  Ops compose transparently with jit capture because values may be
tracers.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import flags as _flags
from ..framework import autograd_engine as _engine
from ..framework.dygraph import is_grad_enabled
from ..framework.tensor import Tensor
from ..observability import metrics as _metrics

_M_DISPATCH_OPS = _metrics.counter(
    "dispatch.ops", "eager dispatches per op name")
_M_DISPATCH_FASTPATH = _metrics.counter(
    "dispatch.fastpath", "per-op jitted-program cache hits/misses")
# hot-loop instruments use pre-frozen label keys (no kwargs, no sort);
# the explicit _ENABLED read keeps the disabled cost to one global load
_DISPATCH_KEYS: Dict[str, tuple] = {}
_FP_HIT_KEY = (("kind", "hit"),)
_FP_MISS_KEY = (("kind", "miss"),)

__all__ = ["OpDef", "register_op", "get_op", "dispatch", "set_autocast_hook",
           "list_ops"]


class OpDef:
    __slots__ = ("name", "fwd", "custom_vjp", "n_inputs", "tags")

    def __init__(self, name: str, fwd: Callable, custom_vjp: Optional[Callable],
                 tags: Tuple[str, ...]):
        self.name = name
        self.fwd = fwd
        self.custom_vjp = custom_vjp
        self.tags = tags


_OPS: Dict[str, OpDef] = {}

# Hook installed by paddle_tpu.amp: (op_name, dtypes) -> target dtype or None.
_autocast_hook: Optional[Callable] = None

# Hook installed by paddle_tpu.jit during the state-discovery pass: receives
# the list of leaf Tensors feeding each op so capture can lift concrete
# tensors (params, buffers) into program inputs.
_trace_recorder: Optional[Callable] = None


def set_autocast_hook(fn: Optional[Callable]) -> None:
    global _autocast_hook
    _autocast_hook = fn


_trace_out_recorder: Optional[Callable] = None

# The recorder hooks are process-global but a capture (to_static state
# discovery, recompute saved-tensor recording) is a single-thread affair:
# ops dispatched concurrently by OTHER threads — the dataloader's
# device-prefetch producer fetching the next batch — must not leak into
# the recording.  Each hook remembers its installer's thread id and
# dispatch only fires it from that thread.
_trace_recorder_tid: Optional[int] = None
_trace_out_recorder_tid: Optional[int] = None

# Sink dict for per-op call counting (amp.debugging.collect_operator_stats).
_op_stats_sink: Optional[Dict[str, int]] = None


def set_trace_recorder(fn: Optional[Callable]) -> None:
    global _trace_recorder, _trace_recorder_tid
    _trace_recorder = fn
    _trace_recorder_tid = threading.get_ident() if fn is not None else None


def set_trace_out_recorder(fn: Optional[Callable]) -> None:
    global _trace_out_recorder, _trace_out_recorder_tid
    _trace_out_recorder = fn
    _trace_out_recorder_tid = threading.get_ident() if fn is not None \
        else None


def set_op_stats_sink(sink: Optional[Dict[str, int]]) -> None:
    global _op_stats_sink
    _op_stats_sink = sink


# Profiler hook: called with (op_name, host_seconds) per eager dispatch.
_op_timer: Optional[Callable] = None


def set_op_timer(fn: Optional[Callable]) -> None:
    global _op_timer
    _op_timer = fn


# paddle.static Program recorder: called with (name, diff_inputs, static,
# outs) after each eager dispatch while a Program is being built.
_program_recorder: Optional[Callable] = None


def set_program_recorder(fn: Optional[Callable]) -> None:
    global _program_recorder
    _program_recorder = fn


# composite-op names whose dispatch is substituted by their primitive
# decomposition rule (decomposition.enabled() sets/clears this)
_decomp_active: Optional[set] = None


def set_decomp_active(names: Optional[set]) -> None:
    global _decomp_active
    _decomp_active = names


def register_op(name: str, fwd: Callable, custom_vjp: Optional[Callable] = None,
                tags: Sequence[str] = ()) -> OpDef:
    op = OpDef(name, fwd, custom_vjp, tuple(tags))
    _OPS[name] = op
    return op


def get_op(name: str) -> OpDef:
    return _OPS[name]


def list_ops() -> List[str]:
    return sorted(_OPS)


def _is_tensor_leaf(x) -> bool:
    return isinstance(x, Tensor)


def _flatten_inputs(diff_inputs):
    """Flatten nested (tuple/list of) Tensor/array inputs.

    Returns (vals_flat, leaves, treedef): leaves[i] is the Tensor for that
    slot or None for raw arrays/scalars.
    """
    flat, treedef = jax.tree_util.tree_flatten(
        list(diff_inputs), is_leaf=_is_tensor_leaf)
    vals = []
    leaves: List[Optional[Tensor]] = []
    for x in flat:
        if isinstance(x, Tensor):
            vals.append(x._value)
            leaves.append(x)
        else:
            vals.append(x)
            leaves.append(None)
    return vals, leaves, treedef


_nan_check_ring: List = []  # [(op_name, device_flag_scalar)]
_nan_atexit_registered = False


def _on_nan_flag_change(enabled):
    """Turning the checker off is a sync point: pending deferred flags are
    reported now, and cannot leak into a later re-enabled phase."""
    if not enabled and _nan_check_ring:
        flush_nan_checks()


def flush_nan_checks():
    """Sync the deferred on-device NaN/Inf flags and report offenders.

    With check_nan_inf_stride > 1, per-op checks stay on device (one
    fused any(~isfinite) reduction per output, no host round trip — the
    reference's on-device reduction design, `nan_inf_utils_detail.cu`);
    this is the single blocking read for the whole window.
    """
    global _nan_check_ring
    ring, _nan_check_ring = _nan_check_ring, []
    if not ring:
        return
    flags_host = jax.device_get(jnp.stack([f for _, f in ring]))
    bad = [name for (name, _), b in zip(ring, flags_host) if b]
    if bad:
        level = _flags.get_flag("check_nan_inf_level")
        msg = f"Ops {sorted(set(bad))} produced NaN/Inf outputs"
        if level == 0:
            raise FloatingPointError(msg)
        import warnings
        warnings.warn(msg)


def _check_nan_inf(name: str, outs):
    level = _flags.get_flag("check_nan_inf_level")
    stride = int(_flags.get_flag("check_nan_inf_stride") or 1)
    if stride <= 1 and _nan_check_ring:
        flush_nan_checks()  # stride was lowered: report strandees now
    for o in outs:
        if isinstance(o, jax.core.Tracer):
            # inside a jit/to_static trace there is no concrete value to
            # test (and a deferred tracer would escape the trace); the
            # captured program is validated by its eager warmup run
            continue
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating):
            flag = jnp.any(~jnp.isfinite(o))  # device-side, non-blocking
            if stride <= 1:
                if bool(flag):
                    msg = f"Op '{name}' produced NaN/Inf output"
                    if level == 0:
                        raise FloatingPointError(msg)
                    import warnings
                    warnings.warn(msg)
            else:
                global _nan_atexit_registered
                if not _nan_atexit_registered:
                    import atexit
                    atexit.register(flush_nan_checks)
                    _nan_atexit_registered = True
                _nan_check_ring.append((name, flag))
    if stride > 1 and len(_nan_check_ring) >= stride:
        flush_nan_checks()


# --------------------------------------------------------------------------
# Fast path: cached per-(op, tree, attrs) jitted forward/backward programs.
#
# The reference keeps the eager hot loop in C++ (`multiply_fwd_func.cc:39`);
# here the Python cost is hidden by compiling each op ONCE per (input
# structure, static attrs) into two cached XLA executables:
#   fwd(vals)          — the op's lowering, jitted
#   bwd(primals, cot)  — jax.vjp of the op *inside* jit: the forward is
#                        recomputed at op granularity and XLA dead-code-
#                        eliminates whatever the grad doesn't need (matmul's
#                        bwd keeps exactly its two matmuls), so no residual
#                        closure has to cross the jit boundary.
# Ops with unhashable attrs (e.g. dropout's traced RNG key) or that cannot
# trace (dynamic output shapes: nonzero/unique/masked_select) fall back to
# the direct eager path; a failing op is remembered in _fast_disabled.
# --------------------------------------------------------------------------

_fast_fwd: Dict[Any, Any] = {}
_fast_bwd: Dict[Any, Any] = {}
_fast_disabled: set = set()

# Errors that mean "this op cannot trace under jit" (dynamic output shape /
# value-dependent Python branch) — the only condition that permanently
# disables an op's fast path.  Runtime execution failures (OOM, transient
# device errors) retry eagerly without poisoning the op process-wide.
_TRACE_ERRORS = (jax.errors.ConcretizationTypeError,
                 jax.errors.TracerArrayConversionError,
                 jax.errors.TracerIntegerConversionError,
                 jax.errors.UnexpectedTracerError,
                 jax.errors.NonConcreteBooleanIndexError)


def _freeze_val(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze_val(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze_val(x)) for k, x in v.items()))
    hash(v)  # raises TypeError for arrays and other unhashables
    # carry the type: 0 / 0.0 / False compare equal but close over
    # different-dtype programs (e.g. clip bounds decide output dtype)
    return (type(v).__name__, v)


def _static_key(static: Dict[str, Any]):
    # AMP changes what nested dispatches trace to (composite ops like
    # recompute re-enter the registry during THEIR trace); the backward
    # program traces later, possibly outside the auto_cast context, so the
    # fast path is simply skipped while autocasting — the legacy jax.vjp
    # linearizes at dispatch time, inside the context, which is correct.
    if _autocast_hook is not None:
        return None
    try:
        return tuple(sorted((k, _freeze_val(v)) for k, v in static.items()))
    except TypeError:
        return None


def _fast_programs(name: str, treedef, skey, fn_flat):
    key = (name, treedef, skey)
    fwd = _fast_fwd.get(key)
    if fwd is None:
        _M_DISPATCH_FASTPATH.inc_key(_FP_MISS_KEY)
        fwd = jax.jit(fn_flat)
        _fast_fwd[key] = fwd

        def bwd(primals, cot):
            return jax.vjp(fn_flat, *primals)[1](cot)
        _fast_bwd[key] = jax.jit(bwd)
    elif _metrics._ENABLED:
        _M_DISPATCH_FASTPATH.inc_key(_FP_HIT_KEY)
    return fwd, _fast_bwd[key]


def _autocast_vals(op_name: str, vals: List[Any]):
    """Apply AMP casting to float inputs; returns (vals, cast_back_dtype)."""
    if _autocast_hook is None:
        return vals, None
    target = _autocast_hook(op_name, vals)
    if target is None:
        return vals, None
    out = []
    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) \
                and v.dtype != target:
            out.append(v.astype(target))
        else:
            out.append(v)
    return out, None


def dispatch(name: str, diff_inputs: Sequence[Any], static: Dict[str, Any],
             op: Optional[OpDef] = None):
    """Execute one op eagerly with autograd tracking."""
    if _decomp_active is not None and name in _decomp_active:
        # composite -> primitives substitution (decomposition.enabled()):
        # run the registered primitive rule on Tensors; its constituent
        # ops re-enter dispatch individually (the reference's program
        # decompose pass, applied at the dynamic dispatch seam)
        from ..decomposition import get_decomp
        return get_decomp(name)(*diff_inputs, **static)
    if _op_timer is not None:
        import time as _time
        t0 = _time.perf_counter()
        try:
            outs = _dispatch_impl(name, diff_inputs, static, op)
        finally:
            _op_timer(name, _time.perf_counter() - t0)
    else:
        outs = _dispatch_impl(name, diff_inputs, static, op)
    if _program_recorder is not None:
        _program_recorder(name, diff_inputs, static, outs)
    return outs


def _dispatch_impl(name: str, diff_inputs: Sequence[Any],
                   static: Dict[str, Any], op: Optional[OpDef] = None):
    if op is None:
        op = _OPS[name]
    if _metrics._ENABLED:
        k = _DISPATCH_KEYS.get(name)
        if k is None:
            k = _DISPATCH_KEYS[name] = (("op", name),)
        _M_DISPATCH_OPS.inc_key(k)
    if _op_stats_sink is not None:
        _op_stats_sink[name] = _op_stats_sink.get(name, 0) + 1
    vals, leaves, treedef = _flatten_inputs(diff_inputs)
    if _trace_recorder is not None:
        if threading.get_ident() == _trace_recorder_tid:
            _trace_recorder(leaves)
    vals, _ = _autocast_vals(name, vals)

    requires_grad = is_grad_enabled() and any(
        t is not None and not t.stop_gradient for t in leaves)

    fn = op.fwd

    def fn_flat(*vs):
        args = jax.tree_util.tree_unflatten(treedef, vs)
        return fn(*args, **static)

    skey = None if name in _fast_disabled else _static_key(static)

    if not requires_grad:
        outs = None
        if skey is not None:
            fwd_j, _ = _fast_programs(name, treedef, skey, fn_flat)
            try:
                outs = fwd_j(*vals)
            except _TRACE_ERRORS:
                outs = fn_flat(*vals)  # user error re-raises right here
                # the eager run succeeded, so the op itself is untraceable
                # (dynamic output shape / value-dependent branch): disable
                _fast_disabled.add(name)
            except Exception:
                # runtime execution failure (e.g. RESOURCE_EXHAUSTED) —
                # retry eagerly but DON'T permanently degrade the op
                outs = fn_flat(*vals)
        if outs is None:
            outs = fn_flat(*vals)
        multi = isinstance(outs, tuple)
        outs_t = tuple(outs) if multi else (outs,)
        if _flags.get_flag("check_nan_inf"):
            _check_nan_inf(name, outs_t)
        wrapped = tuple(Tensor._wrap(o, stop_gradient=True) for o in outs_t)
        if _trace_out_recorder is not None:
            if threading.get_ident() == _trace_out_recorder_tid:
                _trace_out_recorder(wrapped)
        return wrapped if multi else wrapped[0]

    if op.custom_vjp is not None:
        outs, vjp_fn = op.custom_vjp(treedef, vals, static)
        make_vjp = lambda v: op.custom_vjp(treedef, v, static)  # noqa: E731
    else:
        outs = None
        if skey is not None:
            fwd_j, bwd_j = _fast_programs(name, treedef, skey, fn_flat)
            try:
                outs = fwd_j(*vals)
            except _TRACE_ERRORS:
                # eager linearization below re-raises genuine user errors
                # (bad shapes); if it succeeds the op itself is untraceable
                # under jit (dynamic output shape / value-dependent branch)
                outs, vjp_fn = jax.vjp(fn_flat, *vals)
                _fast_disabled.add(name)
            except Exception:
                # runtime execution failure: fall back this once without
                # permanently degrading the op to eager dispatch
                outs, vjp_fn = jax.vjp(fn_flat, *vals)
            else:
                primals = tuple(vals)

                def vjp_fn(cot, _p=primals, _bwd=bwd_j, _f=fn_flat):
                    try:
                        return _bwd(_p, cot)
                    except _TRACE_ERRORS:
                        # degrade to the eager linearization rather than
                        # poisoning every later step
                        _fast_disabled.add(name)
                        return jax.vjp(_f, *_p)[1](cot)
                    except Exception:
                        return jax.vjp(_f, *_p)[1](cot)
        if outs is None:
            outs, vjp_fn = jax.vjp(fn_flat, *vals)
        make_vjp = lambda v: jax.vjp(fn_flat, *v)  # noqa: E731

    multi = isinstance(outs, tuple)
    outs_t = tuple(outs) if multi else (outs,)
    if _flags.get_flag("check_nan_inf"):
        _check_nan_inf(name, outs_t)

    node = _engine.OpGradNode(name, len(outs_t), vjp_fn, tuple_out=multi,
                              primal_vals=vals, make_vjp=make_vjp)
    edges: List[Optional[_engine.Edge]] = []
    for t in leaves:
        if t is None or t.stop_gradient:
            edges.append(None)
        elif t._grad_node is not None:
            edges.append(_engine.Edge(t._grad_node, t._output_slot))
        else:
            edges.append(_engine.Edge(t._get_accum_node(), 0))
    node.next_edges = edges

    wrapped = []
    for i, o in enumerate(outs_t):
        node.out_meta[i] = (o.shape, o.dtype)
        w = Tensor._wrap(o, stop_gradient=False)
        w._grad_node = node
        w._output_slot = i
        wrapped.append(w)
    if _trace_out_recorder is not None:
        if threading.get_ident() == _trace_out_recorder_tid:
            _trace_out_recorder(wrapped)
    return tuple(wrapped) if multi else wrapped[0]


def primitive(name: str, custom_vjp: Optional[Callable] = None,
              tags: Sequence[str] = ()):
    """Decorator: register ``fn(*diff_args, **static)`` and return a
    user-facing wrapper that dispatches Tensors through the engine.

    The wrapper separates inputs: positional args are differentiable inputs
    (Tensor / array / nested lists of Tensors), keyword args are static attrs.
    """
    def deco(fn):
        op = register_op(name, fn, custom_vjp, tags)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            kwargs.pop("name", None)
            return dispatch(name, args, kwargs, op)

        wrapper.op = op
        return wrapper
    return deco
