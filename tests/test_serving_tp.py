"""Tensor-parallel serving decode (`inference/tp.py` + ServingEngine
tp_degree — ISSUE 9 tentpole).

Runs on the conftest's 8-virtual-device CPU mesh, the same simulated
world `test_eager_comm.py` uses: the shard_map programs here have the
identical jaxpr/HLO a real tp-degree pod slice runs, minus the
transport.  The acceptance contract is BIT-parity: the TP layout never
splits a contraction dimension (column-parallel weights + all-gather
re-replication), so degree 2 and 4 must reproduce degree 1's token
streams exactly — greedy and seeded-sampled alike.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.flags import flag_guard
from paddle_tpu.inference.serving import Request, ServingEngine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.observability import compile_tracker


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt3_tiny())
    m.eval()
    return m


def _prompts():
    rng = np.random.RandomState(0)
    return rng.randint(1, 1000, (12,)), rng.randint(1, 1000, (30,))


def _serve(model, tp, prefix=False):
    p1, p2 = _prompts()
    eng = ServingEngine(model, max_batch=3, max_context=128,
                        block_size=16, steps_per_tick=2, tp_degree=tp,
                        prefix_cache=prefix)
    reqs = [eng.add_request(Request(p1, max_new_tokens=8)),
            eng.add_request(Request(p2, max_new_tokens=6, do_sample=True,
                                    temperature=0.9, top_k=40, seed=77))]
    eng.run()
    return eng, [list(r.output_ids) for r in reqs]


@pytest.mark.slow   # 20.8s measured (PR 14 re-budget): compiles three
                    # TP program sets; bit-parity stays HARD-gated in
                    # the serving_tp bench rung and the @slow TP2
                    # composition pins
def test_tp_degree_2_and_4_bit_identical_to_degree_1(model):
    """THE acceptance test: the same mixed greedy+sampled workload at
    simulated TP degree 2 and 4 reproduces degree 1's streams token for
    token (greedy bit-identical; the sampled stream is drawn from the
    same replicated logits + request seed, so it is identical too)."""
    eng1, s1 = _serve(model, 1)
    eng2, s2 = _serve(model, 2)
    eng4, s4 = _serve(model, 4)
    assert s2 == s1
    assert s4 == s1
    assert eng1.stats()["tp_degree"] == 1
    assert eng2.stats()["tp_degree"] == 2
    assert eng4.stats()["tp_degree"] == 4
    # scheduler invariants hold identically across degrees
    for eng in (eng2, eng4):
        assert eng.stats()["free_blocks"] == eng.num_blocks
        assert eng.stats()["reserved"] == 0


def test_tp_weights_and_pools_are_sharded(model):
    """The memory story: each rank holds 1/tp of every sharded matrix
    and of every KV pool (head axis)."""
    eng = ServingEngine(model, max_batch=2, max_context=64,
                        block_size=16, tp_degree=2)
    qkv = eng._tp_params["blocks"][0]["qkv_w"]
    assert "tp" in str(qkv.sharding.spec)
    # per-device shard bytes = half the global array
    shard = qkv.addressable_shards[0].data
    assert shard.size * 2 == qkv.size
    kp, _ = eng.pools[0]
    pshard = kp.addressable_shards[0].data
    assert pshard.shape[0] * 2 == kp.shape[0]      # heads split
    assert pshard.shape[1:] == kp.shape[1:]
    # replicated scheduler inputs: ln params stay whole everywhere
    ln = eng._tp_params["blocks"][0]["ln1_w"]
    assert ln.addressable_shards[0].data.shape == ln.shape


@pytest.mark.slow   # 8.8s measured (PR 14 re-budget): TP warmup grid;
                    # the degree-1 zero-compile pins stay fast
def test_tp_warmup_grid_zero_postwarmup_compiles(model):
    """TP programs enumerate into the PR 7 warmup grid: after warmup()
    a TP engine serves traffic — including a prefix-cache hit and the
    CoW path — with ZERO compile-tracker events."""
    with flag_guard(serving_pad_buckets="16,32,64"):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16, steps_per_tick=1, tp_degree=2,
                            prefix_cache=True)
        info = eng.warmup()
        # tick k=1, host-sampling decode, 3 prefill + 3 suffix-prefill
        # buckets, the CoW copy
        assert info["programs"] == 9
        before = compile_tracker.total_compiles()
        rng = np.random.RandomState(5)
        sysp = list(rng.randint(1, 1000, (32,)))
        a = eng.add_request(Request(sysp + [7, 8], max_new_tokens=4))
        eng.run()
        b = eng.add_request(Request(sysp + [9], max_new_tokens=4))
        eng.run()
        c = eng.add_request(Request(sysp, max_new_tokens=4))  # CoW
        eng.run()
        assert compile_tracker.total_compiles() == before
        st = eng.stats()
        assert st["prefix_cache"]["hits"] == 2
        assert all(len(r.output_ids) == 4 for r in (a, b, c))


@pytest.mark.slow   # 7.0s measured (PR 14 re-budget): TP x prefix
                    # composition; covered by the @slow serving_tp
                    # schema gate (prefix_hit_speedup + parity)
def test_tp_prefix_hit_stream_matches_degree_1_miss(model):
    """Compose: a TP-degree-2 engine WITH prefix reuse serves the same
    tokens as a degree-1 engine WITHOUT it."""
    rng = np.random.RandomState(9)
    sysp = list(rng.randint(1, 1000, (32,)))
    prompt = sysp + [3, 1, 4]

    def serve(tp, prefix, warm_first):
        eng = ServingEngine(model, max_batch=2, max_context=128,
                            block_size=16, tp_degree=tp,
                            prefix_cache=prefix)
        if warm_first:   # make the second admission a genuine hit
            w = eng.add_request(Request(sysp + [9, 9], max_new_tokens=3))
            eng.run()
            assert w.done
        r = eng.add_request(Request(prompt, max_new_tokens=6))
        eng.run()
        if prefix:
            assert eng.stats()["prefix_cache"]["hits"] >= 1
            assert r._prefix_blocks == 2
        return list(r.output_ids)

    baseline = serve(1, False, False)
    assert serve(2, True, True) == baseline


def test_tp_validation_errors(model):
    with pytest.raises(ValueError, match="devices"):
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      tp_degree=16)
    with pytest.raises(ValueError, match="divide"):
        # gpt3_tiny has 4 heads: degree 3 cannot shard them
        ServingEngine(model, max_batch=2, max_context=64, block_size=16,
                      tp_degree=3)

    class NotAGPT:
        cfg = model.cfg

    with pytest.raises(ValueError, match="GPT-family"):
        from paddle_tpu.inference.tp import build_plan
        build_plan(NotAGPT(), 2)


@pytest.mark.slow  # 7s measured: constructs a second (tp) engine; plan-shape and flag-validation tests stay fast
def test_tp_flag_routes_engine_construction(model):
    with flag_guard(serving_tp_degree=2):
        eng = ServingEngine(model, max_batch=2, max_context=64,
                            block_size=16)
    assert eng.tp == 2 and eng._tp_mesh is not None
    p1, _ = _prompts()
    r = eng.add_request(Request(p1, max_new_tokens=4))
    eng.run()
    assert r.done and len(r.output_ids) == 4
