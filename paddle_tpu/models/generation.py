"""Autoregressive generation with KV caches.

Parity: the reference's `paddlenlp`-style `model.generate` surface
(greedy / temperature / top-k / top-p sampling, eos early stop) reduced to
the decoding core.  Eager host loop over single-token steps: the prefill
runs the full prompt once, then each step feeds one token against the
per-layer KV caches (attention is O(1) new work per step).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..framework import random as _random
from ..framework.tensor import Tensor

__all__ = ["GenerationMixin"]


def _process_logits_rows(logits, temperature, top_k, top_p):
    """Row-wise `_process_logits`: every sampling parameter is a [B]
    array, so one compiled program can filter a batch whose rows carry
    DIFFERENT temperature/top-k/top-p (the serving engine's per-slot
    sampling inputs).  Rows with ``top_k <= 0`` / ``top_p >= 1`` skip
    that filter, matching the scalar version's Python branches, and the
    top-p cutoff is computed on the already top-k-filtered logits in the
    same order the scalar version applies them.

    logits: jnp (B, V) float; temperature/top_p float [B]; top_k int [B].
    """
    V = logits.shape[-1]
    logits = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # top-k: threshold at the k-th largest (ascending index V - k)
    asc = jnp.sort(logits, axis=-1)
    kth = jnp.take_along_axis(
        asc, jnp.clip(V - top_k, 0, V - 1)[:, None], axis=-1)
    logits = jnp.where((top_k > 0)[:, None] & (logits < kth),
                       -jnp.inf, logits)
    # top-p: smallest set with cumulative prob >= top_p, over the
    # top-k-filtered distribution (exp(-inf) rows contribute 0)
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jnp.exp(sorted_l - jnp.max(sorted_l, axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(jnp.sum(cum < top_p[:, None], axis=-1), 0, V - 1)
    pth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
    logits = jnp.where((top_p < 1.0)[:, None] & (logits < pth),
                       -jnp.inf, logits)
    return logits


def _process_logits_tokens(logits, temperature, top_k, top_p):
    """k-token twin of `_process_logits_rows` for the speculative-decode
    verify forward: ``logits`` is [B, S, V] (one row per scored chunk
    position) and each SLOT's sampling params apply to every one of its
    S positions.  Row-major flatten keeps slot b's position s at index
    ``b * S + s``, so `jnp.repeat(params, S)` lines the params up with
    the flattened rows exactly.

    logits: jnp (B, S, V) float; temperature/top_p float [B]; top_k
    int [B].  Returns filtered logits, same shape.
    """
    B, S, V = logits.shape
    rows = _process_logits_rows(
        logits.reshape(B * S, V), jnp.repeat(temperature, S),
        jnp.repeat(top_k, S), jnp.repeat(top_p, S))
    return rows.reshape(B, S, V)


def _process_logits(logits, temperature, top_k, top_p):
    """logits: jnp (B, V) -> filtered logits ready for sampling."""
    if temperature != 1.0:
        logits = logits / max(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jnp.exp(sorted_l - jnp.max(sorted_l, axis=-1, keepdims=True))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        kth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return logits


class GenerationMixin:
    """Requires the model to implement
    `forward_with_cache(input_ids, caches, pos_offset) -> (logits, caches)`
    and `init_caches(batch_size) -> caches`."""

    def _compiled_generate(self, ids, max_new_tokens, do_sample,
                           temperature, top_k, top_p, eos_token_id,
                           cache_impl="static"):
        """Whole-generation XLA program: prefill + a `lax.scan` over
        decode steps compile into ONE dispatch.

        The eager host loop pays a host->device round trip per op per
        token — through a tunneled device that is thousands of
        dispatches; here the entire generation is one program (the
        design the reference serves through its fused decoding ops,
        `fused_multi_transformer_op.cu`).  Sequences that hit eos are
        padded with eos to the full length (same contract as the eager
        loop's docstring; no early host exit inside a compiled loop).

        cache_impl="static": fixed [B, max_seq_len] buffers.
        cache_impl="paged": `PagedKVCache` block pool sized to
        prompt + max_new_tokens; the pools and seq_lens ride the scan
        carry, the paged Pallas kernel attends through the block table —
        the reference's `block_multi_head_attention` seat, compiled."""
        import jax
        from ..framework.dygraph import no_grad

        cap = getattr(getattr(self, "cfg", None), "max_seq_len", None)
        if cap is not None and ids.shape[1] + max_new_tokens > cap:
            # inside the compiled loop the cache length is a tracer, so the
            # eager overflow guard can't fire — check before compiling
            # (position embeddings bound BOTH cache impls)
            raise ValueError(
                f"prompt ({ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len ({cap})")
        sd = self.state_dict()
        keys = sorted(sd.keys())
        cache_key = (tuple(ids.shape), max_new_tokens, bool(do_sample),
                     float(temperature), int(top_k), float(top_p),
                     eos_token_id, str(ids.dtype), cache_impl)
        store = getattr(self, "_static_gen_programs", None)
        if store is None:
            store = self._static_gen_programs = {}
        fn = store.get(cache_key)
        if fn is None:
            init_kwargs = {"cache_impl": cache_impl}
            if cache_impl == "paged":
                init_kwargs["max_context"] = \
                    ids.shape[1] + max_new_tokens

            def gen(param_vals, pids, rng_key):
                for kk, vv in zip(keys, param_vals):
                    sd[kk]._value = vv
                B, prompt_len = pids.shape
                with no_grad():
                    caches = self.init_caches(B, **init_kwargs)
                    logits_t, caches = self.forward_with_cache(
                        Tensor._wrap(pids), caches, pos_offset=0)
                logits0 = logits_t._value[:, -1, :]
                finished0 = jnp.zeros((B,), bool)

                def body(carry, step):
                    logits, caches, finished = carry
                    if do_sample:
                        filtered = _process_logits(
                            logits.astype(jnp.float32), temperature,
                            top_k, top_p)
                        nxt = jax.random.categorical(
                            jax.random.fold_in(rng_key, step), filtered,
                            axis=-1)
                    else:
                        nxt = jnp.argmax(logits, axis=-1)
                    nxt = nxt.astype(pids.dtype)
                    if eos_token_id is not None:
                        nxt = jnp.where(finished, eos_token_id, nxt)
                        finished = finished | (nxt == eos_token_id)
                    lt, caches = self.forward_with_cache(
                        Tensor._wrap(nxt[:, None]), caches,
                        pos_offset=prompt_len + step)
                    return (lt._value[:, -1, :], caches, finished), nxt

                with no_grad():
                    (_, _, _), toks = jax.lax.scan(
                        body, (logits0, caches, finished0),
                        jnp.arange(max_new_tokens))
                return jnp.concatenate([pids, toks.T], axis=1)

            fn = store[cache_key] = jax.jit(gen)
        orig = {k: sd[k]._value for k in keys}
        try:
            import jax as _jax
            key = _random.next_key() if do_sample else _jax.random.key(0)
            out = fn([orig[k] for k in keys], ids, key)
            return Tensor._wrap(out)
        finally:
            for k in keys:
                sd[k]._value = orig[k]

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 cache_impl: str = "dense") -> Tensor:
        """Returns (B, prompt_len + <=max_new_tokens) int ids; after a
        sequence hits eos it is padded with eos.

        cache_impl="paged" (models supporting it) decodes against
        block-paged KV caches via the Pallas paged-attention kernel
        inside the whole-generation compiled program; "paged_eager"
        keeps the host decode loop over a `BlockKVCache` (the
        continuous-batching building block with free()/join)."""
        was_training = self.training
        self.eval()
        try:
            ids = input_ids._value if isinstance(input_ids, Tensor) \
                else jnp.asarray(input_ids)
            if ids.ndim == 1:
                ids = ids[None, :]
            B, prompt_len = ids.shape
            import inspect
            sig = inspect.signature(self.init_caches)
            if cache_impl in ("static", "paged") \
                    and "cache_impl" in sig.parameters \
                    and ("max_context" in sig.parameters
                         or cache_impl == "static"):
                return self._compiled_generate(
                    ids, max_new_tokens, do_sample, temperature, top_k,
                    top_p, eos_token_id, cache_impl=cache_impl)
            if cache_impl == "paged_eager":
                cache_impl = "paged"  # host-loop BlockKVCache path
            if "cache_impl" in sig.parameters:
                caches = self.init_caches(B, cache_impl=cache_impl)
            elif cache_impl != "dense":
                raise ValueError(
                    f"{type(self).__name__} supports only dense caches")
            else:
                caches = self.init_caches(B)
            logits_t, caches = self.forward_with_cache(
                Tensor._wrap(ids), caches, pos_offset=0)
            logits = logits_t._value[:, -1, :]

            out = [ids]
            finished = jnp.zeros((B,), bool)
            for step in range(max_new_tokens):
                if do_sample:
                    filtered = _process_logits(
                        logits.astype(jnp.float32), temperature, top_k,
                        top_p)
                    import jax
                    nxt = jax.random.categorical(_random.next_key(),
                                                 filtered, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                nxt = nxt.astype(ids.dtype)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                out.append(nxt[:, None])
                if eos_token_id is not None and bool(finished.all()):
                    break
                logits_t, caches = self.forward_with_cache(
                    Tensor._wrap(nxt[:, None]), caches,
                    pos_offset=prompt_len + step)
                logits = logits_t._value[:, -1, :]
            return Tensor._wrap(jnp.concatenate(out, axis=1))
        finally:
            if was_training:
                self.train()
