"""paddle.vision.ops — populated from the YAML single source
(namespace: vision_ops).  Parity: python/paddle/vision/ops.py."""


# ---- ops from the YAML single source ----
from paddle_tpu.ops.generated_ops import export_namespace as _exp  # noqa: E402
_exp(globals(), "vision_ops")
del _exp
