"""Sparse conv/pool functionals over COO voxel tensors.

Parity: `python/paddle/sparse/nn/functional/conv.py` (conv3d `:24`,
subm_conv3d, conv2d variants) and `pooling.py` (max_pool3d), kernels
`paddle/phi/kernels/sparse/conv_kernel.h` / `gpu/conv_kernel.cu`.

TPU formulation: the GATHER-GEMM-SCATTER decomposition.  The rulebook
(which input voxel feeds which output voxel under each kernel offset) is
built on the HOST from the integer indices — the reference builds it on
GPU with hash tables; indices here are host-known by design, and the
FLOP-carrying work (one [nnz_k, Cin] x [Cin, Cout] matmul per offset)
lands on the MXU through the dense op registry, so the whole conv is
differentiable toward features AND weights with no sparse grad kernels.

Layout: indices [nnz, 1 + d] = (batch, spatial...), values [nnz, Cin],
dense shape (N, *spatial, C) — the reference's NDHWC sparse layout.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops import creation as _c, manipulation as _m
from ..creation import SparseCooTensor

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d", "max_pool3d"]


def _tup(v, d):
    return (v,) * d if isinstance(v, int) else tuple(v)


def _rulebook(indices, spatial, kernel, stride, padding, subm):
    """Per-offset (in_rows, out_rows) pairs + the output index set.

    indices: np [nnz, 1 + d]; returns (out_indices [m, 1 + d],
    rules: list over K of (np in_rows, np out_rows)).  Fully vectorized
    numpy (linearized coords + unique + searchsorted) — no per-voxel
    Python loops (this host has one core)."""
    d = len(spatial)
    idx = np.asarray(indices, np.int64)
    n_batch = int(idx[:, 0].max()) + 1 if len(idx) else 1
    offsets = list(itertools.product(*[range(k) for k in kernel]))
    if subm:
        ospatial = spatial
        center = np.asarray([k // 2 for k in kernel])
    else:
        ospatial = tuple((spatial[i] + 2 * padding[i] - kernel[i])
                         // stride[i] + 1 for i in range(d))
        center = None
    odims = (n_batch,) + tuple(ospatial)

    def targets(off):
        """(valid mask, linearized output coord) per input row."""
        if subm:
            tgt = idx[:, 1:] - (np.asarray(off) - center)
            valid = np.all((tgt >= 0) & (tgt < np.asarray(ospatial)),
                           axis=1)
        else:
            shifted = idx[:, 1:] + np.asarray(padding) - np.asarray(off)
            valid = np.all(shifted % np.asarray(stride) == 0, axis=1)
            tgt = shifted // np.asarray(stride)
            valid &= np.all((tgt >= 0) & (tgt < np.asarray(ospatial)),
                            axis=1)
        tgt = np.clip(tgt, 0, np.asarray(ospatial) - 1)
        lin = np.ravel_multi_index(
            (idx[:, 0],) + tuple(tgt.T), odims)
        return valid, lin

    if subm:
        out_idx = idx.astype(np.int32)
        out_lin = np.ravel_multi_index(
            (idx[:, 0],) + tuple(idx[:, 1:].T), odims)
    else:
        pieces = []
        for off in offsets:
            valid, lin = targets(off)
            pieces.append(lin[valid])
        all_lin = np.concatenate(pieces) if pieces else \
            np.zeros((0,), np.int64)
        out_lin = np.unique(all_lin)
        out_idx = np.stack(np.unravel_index(out_lin, odims),
                           axis=1).astype(np.int32)
    order = np.argsort(out_lin, kind="stable")
    sorted_lin = out_lin[order]
    rules = []
    for off in offsets:
        valid, lin = targets(off)
        pos = np.searchsorted(sorted_lin, lin)
        pos_c = np.clip(pos, 0, max(len(sorted_lin) - 1, 0))
        hit = valid & (pos < len(sorted_lin)) & (sorted_lin[pos_c] == lin)
        in_rows = np.nonzero(hit)[0].astype(np.int64)
        out_rows = order[pos_c[hit]].astype(np.int64)
        rules.append((in_rows, out_rows))
    return out_idx, rules


def _sparse_conv(x: SparseCooTensor, weight, bias, stride, padding, subm,
                 d, dilation=1, groups=1):
    if _tup(dilation, d) != (1,) * d:
        raise NotImplementedError("sparse conv: dilation=1 only")
    if groups != 1:
        raise NotImplementedError("sparse conv: groups=1 only")
    kernel = tuple(int(k) for k in weight.shape[:d])
    cin, cout = int(weight.shape[d]), int(weight.shape[d + 1])
    stride = _tup(stride, d)
    padding = _tup(padding, d)
    spatial = tuple(x._shape[1:1 + d])
    out_idx, rules = _rulebook(x._indices, spatial, kernel, stride,
                               padding, subm)
    m = len(out_idx)
    wmat = _m.reshape(weight, [len(rules), cin, cout])
    out_vals = _c.zeros([m, cout], dtype=str(x.dtype))
    vals = x.values()
    for k, (in_rows, out_rows) in enumerate(rules):
        if len(in_rows) == 0:
            continue
        g = _m.gather(vals, Tensor._wrap(jnp.asarray(in_rows)), axis=0)
        wk = wmat[k]                                   # [Cin, Cout]
        from ...ops import linalg as _l
        contrib = _l.matmul(g, wk)                     # MXU
        out_vals = _m.scatter_nd_add(
            out_vals, Tensor._wrap(jnp.asarray(out_rows.reshape(-1, 1))),
            contrib)
    if bias is not None:
        out_vals = out_vals + _m.reshape(bias, [1, -1])
    if subm:
        oshape = x._shape[:-1] + (cout,)
    else:
        ospatial = tuple((spatial[i] + 2 * padding[i] - kernel[i])
                         // stride[i] + 1 for i in range(d))
        oshape = (x._shape[0],) + ospatial + (cout,)
    return SparseCooTensor(out_idx, out_vals, oshape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3-D convolution; weight [kd, kh, kw, Cin, Cout]."""
    return _sparse_conv(x, weight, bias, stride, padding, subm=False, d=3,
                        dilation=dilation, groups=groups)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: output sites == input sites, so sparsity
    never dilates (Graham & van der Maaten 2017)."""
    return _sparse_conv(x, weight, bias, 1, _tup(padding, 3), subm=True,
                        d=3, dilation=dilation, groups=groups)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    return _sparse_conv(x, weight, bias, stride, padding, subm=False, d=2,
                        dilation=dilation, groups=groups)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _sparse_conv(x, weight, bias, 1, _tup(padding, 2), subm=True,
                        d=2, dilation=dilation, groups=groups)


def max_pool3d(x: SparseCooTensor, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling: per output voxel, the max over its present
    input voxels (absent voxels do not contribute zeros — the
    reference's sparse pooling semantics)."""
    d = 3
    kernel = _tup(kernel_size, d)
    stride = _tup(stride if stride is not None else kernel_size, d)
    padding = _tup(padding, d)
    spatial = tuple(x._shape[1:1 + d])
    out_idx, rules = _rulebook(x._indices, spatial, kernel, stride,
                               padding, subm=False)
    m = len(out_idx)
    c = int(x._shape[-1])
    vals = x.values()
    # dtype-aware floor (fp16 would overflow a hardcoded -3e38 to -inf,
    # and arithmetic blends with -inf produce NaN)
    lowest = float(jnp.finfo(jnp.dtype(str(x.dtype))).min)
    neg = _c.full([m, c], lowest, dtype=str(x.dtype))
    out_vals = neg
    from ...ops import math as _math
    for in_rows, out_rows in rules:
        if len(in_rows) == 0:
            continue
        # each output row appears at most once per offset (the per-offset
        # in->out map is injective), so a gather composition builds the
        # per-offset dense-over-outputs candidate
        slot = np.full((m,), -1, np.int64)
        slot[out_rows] = in_rows
        present = slot >= 0
        g = _m.gather(vals, Tensor._wrap(jnp.asarray(
            np.where(present, slot, 0))), axis=0)
        mask_b = Tensor._wrap(jnp.asarray(present.reshape(-1, 1)))
        cand = _m.where(mask_b, g, neg)
        out_vals = _math.maximum(out_vals, cand)
    ospatial = tuple((spatial[i] + 2 * padding[i] - kernel[i])
                     // stride[i] + 1 for i in range(d))
    oshape = (x._shape[0],) + ospatial + (c,)
    return SparseCooTensor(out_idx, out_vals, oshape)
