"""paddle.save / paddle.load.

Parity: `python/paddle/framework/io.py:723,:960` — pickled state dicts with
Tensors converted to numpy on save and restored as Tensors on load.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .tensor import Tensor

__all__ = ["save", "load"]

_MAGIC = b"PDTPU1\n"


def _to_host(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    try:
        import jax
        if isinstance(obj, jax.Array):
            return {"__tensor__": True, "data": np.asarray(obj),
                    "stop_gradient": True}
    except ImportError:
        pass
    return obj


def _from_host(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            return Tensor(obj["data"], stop_gradient=obj.get("stop_gradient",
                                                             True))
        return {k: _from_host(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_host(v, return_numpy) for v in obj)
    if isinstance(obj, np.ndarray) and not return_numpy \
            and (obj.dtype.kind in "biuf" and obj.dtype.itemsize <= 4
                 or obj.dtype == np.complex64):
        # upstream paddle.save pickles bare numpy arrays in state dicts;
        # match reference load semantics by returning Tensors. 64-bit
        # arrays pass through as numpy: x32 canonicalization would
        # silently narrow them (int64 ids, float64 stats)
        return Tensor(obj, stop_gradient=True)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """Serialize `obj` (nested dicts/lists of Tensors/arrays/...) to a
    path OR a writable file-like object (reference io.py:723 accepts
    both; BytesIO round-trips support in-memory checkpoint shipping)."""
    if not isinstance(protocol, int) or not 2 <= protocol <= 5:
        raise ValueError(f"protocol must be 2..5, got {protocol!r}")
    if hasattr(path, "write"):
        path.write(_MAGIC)
        pickle.dump(_to_host(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_to_host(obj), f, protocol=protocol)


def _load_stream(f, return_numpy):
    head = f.read(len(_MAGIC))
    if head != _MAGIC:
        f.seek(0)
    obj = pickle.load(f)
    return _from_host(obj, return_numpy)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    """Load from a path or a readable file-like object."""
    if hasattr(path, "read"):
        return _load_stream(path, return_numpy)
    with open(path, "rb") as f:
        return _load_stream(f, return_numpy)
