"""init_parallel_env + DataParallel.

Parity: `python/paddle/distributed/parallel.py` (init_parallel_env `:943`,
DataParallel `:202` + C++ EagerReducer
`fluid/distributed/collective/reducer.h:88`).

TPU-native DataParallel: parameters stay replicated on the mesh; input
batches are sharded over the 'dp' axis (shard_batch); the gradient
all-reduce the reference implements with bucketed NCCL calls is inserted by
GSPMD when the sharded-batch loss is differentiated — eagerly per-op, or
fused inside a captured train step.  no_sync() suppresses the constraint.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from . import env as _env
from . import mesh as _mesh

_heartbeat = None  # rank-liveness publisher; started once per process

__all__ = ["init_parallel_env", "DataParallel", "shard_batch", "ParallelEnv"]

from .env import ParallelEnv  # noqa: F401  (re-export)


def init_parallel_env(backend: Optional[str] = None):
    """Bootstrap the distributed runtime.

    Single-host (tests, 1 chip): builds a trivial mesh over local devices.
    Multi-host: jax.distributed.initialize from the launcher env
    (coordinator address replaces the reference's TCPStore rendezvous)."""
    import os
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # the jax.distributed coordinator is its OWN endpoint (the launcher
    # publishes COORDINATOR_ADDRESS) — PADDLE_MASTER is the TCPStore and
    # cannot double as the coordinator port
    addr = os.environ.get("COORDINATOR_ADDRESS")
    from ..core.jax_compat import distributed_is_initialized
    if addr and world > 1 and not distributed_is_initialized():
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=world,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    if _mesh.get_mesh() is None:
        _mesh.set_mesh(_mesh.build_mesh({"dp": -1}))
    # liveness heartbeat through the launcher's store so watchdog hang
    # reports can name the missing rank (reference Watcher polling);
    # idempotent across repeated init calls, stoppable via its handle
    global _heartbeat
    if _heartbeat is None:
        from .collective import _generation, _host_store
        store = _host_store()
        if store is not None:
            from .watchdog import Heartbeat
            _heartbeat = Heartbeat(
                store, int(os.environ.get("PADDLE_TRAINER_ID", "0")),
                generation=_generation()).start()
    _env._mark_initialized()
    return _env.ParallelEnv()


def shard_batch(tensor: Tensor, axis: str = "dp", dim: int = 0) -> Tensor:
    """Lay a batch out over a mesh axis (the DP input split)."""
    m = _mesh.get_mesh()
    if m is None or axis not in m.axis_names or m.shape[axis] <= 1:
        return tensor
    spec = [None] * tensor.ndim
    spec[dim] = axis
    sh = NamedSharding(m, P(*spec))
    if tensor._is_traced():
        tensor._value = jax.lax.with_sharding_constraint(tensor._value, sh)
    else:
        tensor._value = jax.device_put(tensor._value, sh)
    return tensor


class DataParallel(Layer):
    """DDP wrapper (ref `python/paddle/DataParallel`, reducer.h:88).

    In-process SPMD mode (one process, many devices): forward shards the
    batch over the 'dp' mesh axis and XLA inserts the gradient psums.

    Multi-process eager mode (under `distributed.launch`): each process
    computes grads on its own batch; reducer hooks on every parameter's
    accumulation node all-reduce(avg) the gradient the moment it lands in
    `loss.backward()` — the reference's Reducer, with the cached jitted
    global-array programs of `eager_comm.py` as the transport."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._sync = True
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        from . import eager_comm
        self._multiproc = eager_comm.in_multiprocess()
        if self._multiproc:
            self._register_reducer_hooks()
            self._broadcast_initial_params()

    def _register_reducer_hooks(self):
        from .collective import ReduceOp, all_reduce
        dp = self

        def sync(t):
            if not dp._sync:
                return
            g = t._grad
            if g is not None:
                all_reduce(g, op=ReduceOp.AVG, group=dp._group)

        for p in self._layers.parameters():
            if not p.stop_gradient:
                node = p._get_accum_node()
                node.reducer_hooks.append(sync)

    def _broadcast_initial_params(self):
        """Rank-0 weights AND buffers win at construction (the
        reference's sync_params_buffers: BatchNorm running stats are
        buffers, outside parameters(), and must start identical too)."""
        from .collective import broadcast
        for p in self._layers.parameters():
            broadcast(p, src=0, group=self._group)
        buffers = getattr(self._layers, "buffers", None)
        if callable(buffers):
            for b in buffers():
                broadcast(b, src=0, group=self._group)

    def forward(self, *inputs, **kwargs):
        if self._sync and not self._multiproc:
            inputs = tuple(shard_batch(i) if isinstance(i, Tensor) else i
                           for i in inputs)
            kwargs = {k: shard_batch(v) if isinstance(v, Tensor) else v
                      for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    class _NoSync:
        def __init__(self, dp):
            self.dp = dp

        def __enter__(self):
            self.dp._sync = False
            return self

        def __exit__(self, *exc):
            self.dp._sync = True
            return False

    def no_sync(self):
        """Within this context batches are NOT dp-sharded, so no gradient
        all-reduce is induced (grad accumulation then happens locally)."""
        return DataParallel._NoSync(self)

    # transparent delegation
    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Manual grad sync after `no_sync` accumulation (reference
        `DataParallel.apply_collective_grads`)."""
        if not self._multiproc:
            return
        from .collective import ReduceOp, all_reduce
        for p in self._layers.parameters():
            if p._grad is not None:
                all_reduce(p._grad, op=ReduceOp.AVG, group=self._group)
