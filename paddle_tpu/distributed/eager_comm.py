"""Eager cross-process collectives on global arrays.

Role of the reference's eager ProcessGroup
(`paddle/fluid/distributed/collective/process_group.h:47`,
`process_group_nccl.cc` — every rank calls `all_reduce(tensor)` and NCCL
moves the bytes): in a multi-process JAX job the equivalent is a tiny
cached jitted program over a one-device-per-process mesh:

1. each process wraps its local value as its shard of a global
   [W, *shape] array (`jax.make_array_from_process_local_data`);
2. all processes enter the SAME cached compiled program in lockstep (an
   eager collective call is already a lockstep point — identical to a
   NCCL kernel launch);
3. the program reduces/gathers/permutes over the leading axis with the
   output replicated, and each process reads back its addressable shard.

Programs cache per (op, shape, dtype, group) — after the first call a
collective is one executable launch, the same cost model as a cached
NCCL plan.  These paths are for EAGER tensors between jit regions (DDP
grad sync, metric reduction); code inside shard_map/jit keeps using the
axis-context lowering in `collective.py`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_AXIS = "world"


def in_multiprocess() -> bool:
    return jax.process_count() > 1


def group_size(group) -> int:
    """Number of PARTICIPATING PROCESSES (the eager collective's world;
    a process may own many local devices — e.g. a virtual 8-device CPU
    mesh — but contributes one row)."""
    ranks = group_ranks(group)
    return len(ranks) if ranks is not None else jax.process_count()


def group_ranks(group) -> Optional[Sequence[int]]:
    """Process ids participating; None = every process."""
    if group is None or getattr(group, "_ranks", None) is None:
        return None
    return tuple(group._ranks)


@functools.lru_cache(maxsize=None)
def _group_mesh(ranks: Optional[tuple]) -> Mesh:
    """1-D mesh with ONE device per participating process (a process may
    own several local devices; the collective's unit is the process, as in
    the reference's one-rank-per-GPU model)."""
    per_proc = {}
    for d in jax.devices():
        if ranks is None or d.process_index in ranks:
            cur = per_proc.get(d.process_index)
            if cur is None or d.id < cur.id:
                per_proc[d.process_index] = d
    devs = [per_proc[p] for p in sorted(per_proc)]
    return Mesh(np.array(devs), (_AXIS,))


def row_of(group, global_rank: int) -> int:
    """Row of a GLOBAL process rank in the stacked [W, *shape] layout
    (mesh rows are the group's process ids in sorted order)."""
    ranks = group_ranks(group)
    if ranks is None:
        return global_rank
    return sorted(ranks).index(global_rank)


def my_row(group=None) -> int:
    """This process's row in the stacked [W, *shape] layout."""
    return row_of(group, jax.process_index())


def _stack(mesh: Mesh, value: jax.Array) -> jax.Array:
    """Local [*s] -> global [W, *s], row w owned by process w.

    Assembled from the existing device buffer
    (make_array_from_single_device_arrays) — no host round trip; a DDP
    reducer hook's per-parameter collective stays device-side."""
    sharding = NamedSharding(mesh, P(_AXIS, *([None] * value.ndim)))
    mine = [d for d in mesh.devices.flat
            if d.process_index == jax.process_index()]
    local = jax.device_put(jnp.asarray(value)[None], mine[0])
    W = mesh.devices.size
    return jax.make_array_from_single_device_arrays(
        (W,) + tuple(value.shape), sharding, [local])


def _local_view(garr: jax.Array) -> jax.Array:
    """The replicated result's addressable shard (no host round trip)."""
    return garr.addressable_shards[0].data


_REDUCERS = {
    "sum": lambda x: jnp.sum(x, axis=0),
    "avg": lambda x: jnp.mean(x, axis=0),
    "mean": lambda x: jnp.mean(x, axis=0),
    "max": lambda x: jnp.max(x, axis=0),
    "min": lambda x: jnp.min(x, axis=0),
    "prod": lambda x: jnp.prod(x, axis=0),
}


@functools.lru_cache(maxsize=None)
def _program(kind: str, ranks: Optional[tuple], arg: Optional[int] = None):
    """Cached compiled collective: global [W, *s] in, replicated out."""
    mesh = _group_mesh(ranks)
    rep = NamedSharding(mesh, P())

    if kind in _REDUCERS:
        fn = _REDUCERS[kind]
    elif kind == "broadcast":
        fn = lambda x: x[arg]                          # noqa: E731
    elif kind == "all_gather":
        fn = lambda x: x                               # noqa: E731
    elif kind == "reduce_scatter":
        W = mesh.devices.size

        def fn(x):                                     # [W, W*m, ...]
            s = jnp.sum(x, axis=0)
            return s.reshape((W, -1) + s.shape[1:])    # rows per rank
    elif kind == "alltoall":
        fn = lambda x: jnp.swapaxes(x, 0, 1)           # noqa: E731
    else:  # pragma: no cover
        raise ValueError(kind)
    return jax.jit(fn, out_shardings=rep)


def all_reduce(value: jax.Array, op: str = "sum", group=None) -> jax.Array:
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program(op, ranks)(g))


def broadcast(value: jax.Array, src_row: int, group=None) -> jax.Array:
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program("broadcast", ranks, src_row)(g))


def all_gather(value: jax.Array, group=None) -> jax.Array:
    """Returns the stacked [W, *shape] result (callers split/reshape)."""
    ranks = group_ranks(group)
    g = _stack(_group_mesh(ranks), value)
    return _local_view(_program("all_gather", ranks)(g))


def reduce_scatter(value: jax.Array, op: str = "sum", group=None):
    """value [W*m, ...] per rank; returns this rank's [m, ...] of the
    summed result.  Only sum (the DDP/ZeRO op) is defined, as in the
    reference's reduce-scatter use."""
    if op not in ("sum", "avg", "mean"):
        raise ValueError("reduce_scatter supports sum/avg")
    ranks = group_ranks(group)
    mesh = _group_mesh(ranks)
    g = _stack(mesh, value)
    rows = _local_view(_program("reduce_scatter", ranks)(g))
    out = rows[my_row(group)]
    if op in ("avg", "mean"):
        out = out / mesh.devices.size
    return out


def alltoall(value: jax.Array, group=None) -> jax.Array:
    """value [W, ...] per rank (row r bound for rank r); returns this
    rank's received [W, ...] stack."""
    ranks = group_ranks(group)
    mesh = _group_mesh(ranks)
    g = _stack(mesh, value)                            # [W, W, ...]
    swapped = _local_view(_program("alltoall", ranks)(g))
    return swapped[my_row(group)]
