"""Normalization functionals. Parity: `python/paddle/nn/functional/norm.py`.

layer_norm/rms_norm are single fused XLA expressions; on TPU the compiler
fuses them with surrounding elementwise work (the role of the reference's
`fused_layernorm_kernel.cu` / fused rmsnorm). batch_norm handles running-stat
updates functionally — the Layer owns the buffers."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import dispatch as _d, register_op

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm",
           "rms_norm", "local_response_norm"]


def _layer_norm_impl(x, w, b, *, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out


register_op("layer_norm", _layer_norm_impl, tags=("fused",))


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin_axis = x.ndim - len(normalized_shape)
    return _d("layer_norm", (x, weight, bias),
              {"eps": float(epsilon), "begin_axis": begin_axis})


def _rms_norm_impl(x, w, *, eps, axis):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
    out = (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    if w is not None:
        out = out * w
    return out


register_op("rms_norm", _rms_norm_impl, tags=("fused",))


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1, name=None):
    """RMSNorm (fused; reference ships it as incubate fused_rms_norm)."""
    return _d("rms_norm", (x, weight), {"eps": float(epsilon),
                                        "axis": int(axis)})


def _bn_impl(x, w, b, mean, var, *, eps, channel_axis):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    mean = jnp.reshape(mean, shape)
    var = jnp.reshape(var, shape)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * jnp.reshape(w, shape)
    if b is not None:
        out = out + jnp.reshape(b, shape)
    return out


register_op("batch_norm_apply", _bn_impl, tags=("fused",))


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Functional BN.  In training mode computes batch stats, normalizes with
    them, and updates the running buffers in place (paddle momentum semantics:
    running = momentum*running + (1-momentum)*batch)."""
    channel_axis = 1 if not data_format.endswith("C") else x.ndim - 1
    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        from ...ops import math as _math, manipulation as _m
        axes = [i for i in range(x.ndim) if i != channel_axis]
        batch_mean = _math.mean(x, axis=axes)
        diff = x - _m.reshape(batch_mean, [1 if i != channel_axis else -1
                                           for i in range(x.ndim)])
        batch_var = _math.mean(diff * diff, axis=axes)
        out = _d("batch_norm_apply",
                 (x, weight, bias, batch_mean, batch_var),
                 {"eps": float(epsilon), "channel_axis": channel_axis})
        # update running stats (biased batch variance, matching the
        # reference batch_norm_kernel.cc update rule); expressed through
        # dispatched Tensor ops so jit capture records the buffers as
        # program state (not baked constants)
        from ...framework.dygraph import no_grad
        with no_grad():
            if running_mean is not None:
                new_mean = running_mean * momentum + batch_mean * (1 - momentum)
                # keep the buffer's dtype: autocast must not drift fp32
                # running stats to bf16
                running_mean._value = new_mean._value.astype(
                    running_mean._value.dtype)
            if running_var is not None:
                new_var = running_var * momentum + \
                    batch_var * (1 - momentum)
                running_var._value = new_var._value.astype(
                    running_var._value.dtype)
        return out
    return _d("batch_norm_apply",
              (x, weight, bias, running_mean, running_var),
              {"eps": float(epsilon), "channel_axis": channel_axis})


def _instance_norm_impl(v, w, b, *, eps):
    axes = tuple(range(2, v.ndim))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    out = (v - mean) * jax.lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (v.ndim - 2)
    if w is not None:
        out = out * jnp.reshape(w, shape)
    if b is not None:
        out = out + jnp.reshape(b, shape)
    return out


register_op("instance_norm", _instance_norm_impl, tags=("fused",))


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    return _d("instance_norm", (x, weight, bias), {"eps": float(eps)})


def _group_norm_impl(v, w, b, *, groups, eps, channel_last):
    if channel_last:
        perm = (0, v.ndim - 1) + tuple(range(1, v.ndim - 1))
        v = jnp.transpose(v, perm)
    n, c = v.shape[0], v.shape[1]
    spatial = v.shape[2:]
    g = jnp.reshape(v, (n, groups, c // groups) + spatial)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    g = (g - mean) * jax.lax.rsqrt(var + eps)
    out = jnp.reshape(g, (n, c) + spatial)
    shape = (1, -1) + (1,) * (out.ndim - 2)
    if w is not None:
        out = out * jnp.reshape(w, shape)
    if b is not None:
        out = out + jnp.reshape(b, shape)
    if channel_last:
        inv = (0,) + tuple(range(2, v.ndim)) + (1,)
        out = jnp.transpose(out, inv)
    return out


register_op("group_norm", _group_norm_impl, tags=("fused",))


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    return _d("group_norm", (x, weight, bias),
              {"groups": int(num_groups), "eps": float(epsilon),
               "channel_last": data_format.endswith("C")})


def _lrn_impl(v, *, size, alpha, beta, k):
    sq = jnp.square(v)
    half = size // 2
    # sum over a window of channels (NCHW dim 1)
    pads = [(0, 0)] * v.ndim
    pads[1] = (half, size - 1 - half)
    sq_pad = jnp.pad(sq, pads)
    win = [1] * v.ndim
    win[1] = size
    acc = jax.lax.reduce_window(sq_pad, 0.0, jax.lax.add, tuple(win),
                                (1,) * v.ndim, "VALID")
    return v / jnp.power(k + alpha * acc, beta)


register_op("local_response_norm", _lrn_impl)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _d("local_response_norm", (x,),
              {"size": int(size), "alpha": float(alpha), "beta": float(beta),
               "k": float(k)})
