"""Paged-KV decode attention as a Pallas TPU kernel.

Role of the reference's `block_multihead_attention` decode path
(`paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu` +
`fluid/operators/fused/fused_multi_transformer_op.cu.h` cache-KV branch):
the KV cache lives in fixed-size physical blocks; each sequence owns a
block table mapping its logical positions to physical blocks, so cache
memory is allocated in pages instead of max-length rectangles.

TPU design: one decode step attends a single query token per sequence over
that sequence's block list.  The kernel runs on a (B*nh, max_blocks) grid
whose LAST dimension is sequential on TPU, carrying the online-softmax
state (m, l, acc) in VMEM scratch across block steps.  The physical block
to stream is chosen by the BlockSpec index_map reading the SCALAR-PREFETCHED
block table — the gather happens in the DMA engine's addressing, not as a
data-plane gather op.  Blocks past ceil(seq_len/bs) are skipped entirely
(`pl.when`), so compute is proportional to the true context length, not
the padded table width.

Non-TPU backends run the same math as one jnp gather + masked softmax
(`paged_attention_reference`), which is also the CI oracle.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["paged_attention", "paged_attention_reference", "BlockKVCache",
           "paged_write_token", "paged_write_prefill",
           "paged_chunk_attention", "paged_chunk_attention_reference",
           "paged_verify_attention"]

_NEG_INF = -1e30


def _claim(name, mode):
    """Record trace-time evidence that a Pallas kernel was emitted.

    Interpret-mode `pallas_call` lowers to a plain `stablehlo.while`
    with no custom-call marker, so the xray HLO scan cannot see it; the
    claims channel is how the kernel-coverage audit learns which kernel
    a program actually traced (no-op outside an audit capture)."""
    from ..observability.xray import claim_kernel
    claim_kernel(name, mode)


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, bs, max_blocks, nh):
    """One grid instance = ALL heads of one sequence against one physical
    block: grid (B, max_blocks), k/v blocks [nh, bs, hd].  Processing the
    whole head dim per instance cuts the sequential grid by nh× and makes
    each DMA nh× larger — the per-iteration launch overhead dominated the
    per-head variant (round 3's kernel) at decode sizes."""
    b = pl.program_id(0)
    blk = pl.program_id(1)

    @pl.when(blk == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq_len = lens_ref[b]
    n_blocks = (seq_len + bs - 1) // bs

    @pl.when(blk < n_blocks)
    def _():
        q = q_ref[:, :]                                   # [nh, hd]
        k = k_ref[:, :, :]                                # [nh, bs, hd]
        # batched matvec as [nh, 1, hd] x [nh, bs, hd]: Mosaic's dot
        # lowering requires a non-empty lhs non-contracting dim set
        s = jax.lax.dot_general(
            q[:, None, :], k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :] * scale  # [nh, bs]
        pos = blk * bs + jax.lax.broadcasted_iota(
            jnp.int32, (nh, bs), 1)
        s = jnp.where(pos < seq_len, s, _NEG_INF)
        m_prev = m_scr[:, 0]                              # [nh]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])                   # [nh, bs]
        alpha = jnp.exp(m_prev - m_new)
        v = v_ref[:, :, :]                                # [nh, bs, hd]
        pv = jax.lax.dot_general(
            p.astype(v.dtype)[:, None, :], v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[:, 0, :]  # [nh, hd]
        acc_scr[:] = acc_scr[:] * alpha[:, None] + pv
        l_scr[:] = l_scr[:] * alpha[:, None] + jnp.broadcast_to(
            jnp.sum(p, axis=1)[:, None], l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)

    @pl.when(blk == max_blocks - 1)
    def _():
        l = l_scr[:, 0]                                   # [nh]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:, :] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_cache, v_cache, block_tables, seq_lens,
                    interpret=None):
    """Decode attention over a paged KV cache.

    q:            [B, nh, hd]        one query token per sequence
    k_cache/v_cache: [nh, num_blocks, bs, hd] physical block pool — heads
        lead so each streamed block is a clean [bs, hd] tile (Mosaic needs
        the trailing two dims tileable; a squeezed head dim between them
        would break that)
    block_tables: [B, max_blocks] int32 physical block ids (pad with 0)
    seq_lens:     [B] int32 current context length per sequence
    Returns [B, nh, hd].
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pltpu is None:  # no pallas TPU lowering available at all
        return paged_attention_reference(q, k_cache, v_cache, block_tables,
                                         seq_lens)
    _claim("paged_decode", "interpret" if interpret else "custom_call")
    B, nh, hd = q.shape
    _, _, bs, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    scale = 1.0 / math.sqrt(hd)

    kern = functools.partial(_decode_kernel, scale=scale, bs=bs,
                             max_blocks=max_blocks, nh=nh)

    def qmap(b, blk, tables, lens):
        return (b, 0, 0)

    def kvmap(b, blk, tables, lens):
        return (0, tables[b, blk], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_blocks),
        in_specs=[
            pl.BlockSpec((None, nh, hd), qmap),
            pl.BlockSpec((nh, None, bs, hd), kvmap),
            pl.BlockSpec((nh, None, bs, hd), kvmap),
        ],
        out_specs=pl.BlockSpec((None, nh, hd), qmap),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, hd), q.dtype),
        interpret=interpret,
    )(block_tables, seq_lens, q, k_cache, v_cache)


def paged_attention_reference(q, k_cache, v_cache, block_tables, seq_lens):
    """Pure-XLA oracle: gather each sequence's blocks, masked softmax."""
    B, nh, hd = q.shape
    _, _, bs, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    # [nh, B, max_blocks, bs, hd] -> [B, S_max, nh, hd]
    k = jnp.moveaxis(k_cache[:, block_tables], 0, 3).reshape(
        B, max_blocks * bs, nh, hd)
    v = jnp.moveaxis(v_cache[:, block_tables], 0, 3).reshape(
        B, max_blocks * bs, nh, hd)
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(hd)
    pos = jnp.arange(max_blocks * bs)[None, None, :]
    live = pos < seq_lens[:, None, None]
    s = jnp.where(live, s, _NEG_INF)
    p = jnp.where(live, jax.nn.softmax(s, axis=-1), 0.0)
    # seq_len == 0: every position masked -> zeros (matching the kernel's
    # l == 0 guard), not a uniform average over pad blocks
    return jnp.einsum("bhs,bshd->bhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def paged_write_token(k_pool, v_pool, tables, seq_lens, k_step, v_step):
    """Traced single-token cache write (the in-place decode store of the
    reference's `fused_multi_transformer_op.cu.h:942-999`, as a
    functional XLA scatter so it can live inside a `lax.scan` carry).

    k_pool/v_pool: [nh, num_blocks, bs, hd]; tables: [B, max_blocks]
    int32; seq_lens: [B] current lengths (write position); k_step/v_step:
    [B, nh, hd].  Returns the updated pools."""
    bs = k_pool.shape[2]
    B = k_step.shape[0]
    slot = seq_lens // bs                                   # [B]
    off = seq_lens % bs                                     # [B]
    blk = tables[jnp.arange(B), slot]                       # [B]
    k_pool = k_pool.at[:, blk, off].set(
        jnp.moveaxis(k_step, 0, 1).astype(k_pool.dtype))
    v_pool = v_pool.at[:, blk, off].set(
        jnp.moveaxis(v_step, 0, 1).astype(v_pool.dtype))
    return k_pool, v_pool


def paged_write_prefill(k_pool, v_pool, tables, k, v):
    """Traced bulk prefill write from empty sequences: k/v [B, S, nh, hd]
    scatter into each sequence's first ceil(S/bs) table blocks (one
    scatter per pool, not per token).  The pad tail of the last block
    stays zero and is masked by seq_lens at attend time."""
    bs = k_pool.shape[2]
    B, S, nh, hd = k.shape
    nb = (S + bs - 1) // bs
    pad = nb * bs - S
    if pad:
        zeros = jnp.zeros((B, pad, nh, hd), k.dtype)
        k = jnp.concatenate([k, zeros], axis=1)
        v = jnp.concatenate([v, zeros], axis=1)
    blks = tables[:, :nb].reshape(-1)                       # [B*nb]
    # [B, nb*bs, nh, hd] -> [nh, B*nb, bs, hd]
    kb = jnp.moveaxis(k.reshape(B * nb, bs, nh, hd), 2, 0)
    vb = jnp.moveaxis(v.reshape(B * nb, bs, nh, hd), 2, 0)
    k_pool = k_pool.at[:, blks].set(kb.astype(k_pool.dtype))
    v_pool = v_pool.at[:, blks].set(vb.astype(v_pool.dtype))
    return k_pool, v_pool


def _chunk_grid_kernel(tables_ref, starts_ref, q_ref, k_ref, v_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, scale, bs, max_blocks,
                       q_blk):
    """Flash-style chunk prefill, grid (B, s/q_blk, max_blocks): one
    instance = one q tile of one sequence against one physical block,
    streamed through the scalar-prefetched table (the DMA does the
    gather, like `_decode_kernel`).  Online-softmax state lives in VMEM
    scratch across the sequential block dimension.  Queries sit at
    absolute positions `start + j` (start = cached prefix length), so
    the causal mask is offset: key position <= query position."""
    b = pl.program_id(0)
    qt = pl.program_id(1)
    blk = pl.program_id(2)

    @pl.when(blk == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = starts_ref[b]
    qpos = start + qt * q_blk + jax.lax.broadcasted_iota(
        jnp.int32, (q_blk, 1), 0)[:, 0]                   # [q_blk]
    qpos_max = start + (qt + 1) * q_blk - 1

    @pl.when(blk * bs <= qpos_max)
    def _():
        q = jnp.transpose(q_ref[...], (1, 0, 2))          # [nh, q_blk, hd]
        k = k_ref[...]                                    # [nh, bs, hd]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [nh, q_blk, bs]
        kpos = blk * bs + jax.lax.broadcasted_iota(
            jnp.int32, (q_blk, bs), 1)
        s = jnp.where((kpos <= qpos[:, None])[None], s, _NEG_INF)
        m_prev = m_scr[:, :]                              # [nh, q_blk]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
        p = jnp.exp(s - m_new[:, :, None])
        alpha = jnp.exp(m_prev - m_new)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [nh, q_blk, hd]
        acc_scr[:] = acc_scr[:] * alpha[:, :, None] + pv
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=2)
        m_scr[:] = m_new

    @pl.when(blk == max_blocks - 1)
    def _():
        l = l_scr[:, :]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scr[:] / l_safe[:, :, None]             # [nh, q_blk, hd]
        o_ref[...] = jnp.transpose(out, (1, 0, 2)).astype(o_ref.dtype)


def _chunk_fused_kernel(tables_ref, starts_ref, q_ref, k_ref, v_ref, o_ref,
                        *, scale, bs, max_blocks, s):
    """Single-pass variant, grid (B,): the whole chunk of one sequence in
    one instance, a `fori_loop` over only the LIVE blocks (trip count
    `ceil((start + s) / bs)` — data-dependent, unlike a grid dimension).

    This is the interpret-mode (CPU fallback) strategy: the interpret
    executor copies every input buffer once per grid step, so a
    per-block grid pays `max_blocks` full k/v-pool copies per sequence
    — linear in POOL size, which loses to the dense gather at any real
    pool.  One grid step per sequence pays the pool copy once and skips
    dead table columns entirely, which is also where the win over dense
    comes from: dense attends the full padded table width."""
    b = pl.program_id(0)
    start = starts_ref[b]
    q = q_ref[...]                                        # [s, nh, hd]
    nh, hd = q.shape[1], q.shape[2]
    q = jnp.transpose(q, (1, 0, 2)).astype(jnp.float32)   # [nh, s, hd]
    qpos = start + jax.lax.broadcasted_iota(jnp.int32, (s, 1), 0)[:, 0]
    n_iter = jnp.minimum((start + s + bs - 1) // bs, max_blocks)

    def body(i, carry):
        m, l, acc = carry
        blk = tables_ref[b, i]
        k = pl.load(k_ref, (slice(None), pl.dslice(blk, 1)))[:, 0]
        v = pl.load(v_ref, (slice(None), pl.dslice(blk, 1)))[:, 0]
        sc = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [nh, s, bs]
        kpos = i * bs + jax.lax.broadcasted_iota(jnp.int32, (s, bs), 1)
        sc = jnp.where((kpos <= qpos[:, None])[None], sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=2))
        p = jnp.exp(sc - m_new[:, :, None])
        alpha = jnp.exp(m - m_new)
        pv = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return (m_new, l * alpha + jnp.sum(p, axis=2),
                acc * alpha[:, :, None] + pv)

    m0 = jnp.full((nh, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((nh, s), jnp.float32)
    a0 = jnp.zeros((nh, s, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, a0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = jnp.transpose(acc / l[:, :, None],
                               (1, 0, 2)).astype(o_ref.dtype)


def paged_chunk_attention(q, k_cache, v_cache, block_tables, start_lens,
                          interpret=None, strategy=None, q_blk=None,
                          _claim_name="paged_chunk_prefill"):
    """Chunked/suffix prefill attention over a paged KV cache.

    q:            [B, s, nh, hd]  chunk queries (s > 1 typical; post-RoPE)
    k_cache/v_cache: [nh, num_blocks, bs, hd] physical block pool with
        the chunk ALREADY WRITTEN at positions start..start+s-1 (the
        write stays the caller's single scatter — `PagedChunkView`)
    block_tables: [B, max_blocks] int32 physical block ids (pad with 0)
    start_lens:   [B] int32 cached-prefix length per sequence; query j
        sits at absolute position start + j and attends keys 0..start+j
        (offset causal mask, `PagedChunkView`'s contract — including the
        overflow rows past the table, which attend the whole table and
        are discarded upstream)
    strategy: "grid" (flash tiles over (B, s-tiles, blocks) — the TPU
        layout) or "fused" (one pass per sequence — the interpret-mode
        layout; see `_chunk_fused_kernel`).  Default: by `interpret`.
    Returns [B, s, nh, hd].
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pltpu is None:  # no pallas TPU lowering available at all
        return paged_chunk_attention_reference(
            q, k_cache, v_cache, block_tables, start_lens)
    if strategy is None:
        strategy = "fused" if interpret else "grid"
    _claim(_claim_name, "interpret" if interpret else "custom_call")
    B, s, nh, hd = q.shape
    bs = k_cache.shape[2]
    max_blocks = block_tables.shape[1]
    scale = 1.0 / math.sqrt(hd)

    if strategy == "fused":
        kern = functools.partial(_chunk_fused_kernel, scale=scale, bs=bs,
                                 max_blocks=max_blocks, s=s)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((None, s, nh, hd),
                             lambda b, tables, starts: (b, 0, 0, 0)),
                pl.BlockSpec(k_cache.shape,
                             lambda b, tables, starts: (0, 0, 0, 0)),
                pl.BlockSpec(v_cache.shape,
                             lambda b, tables, starts: (0, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, s, nh, hd),
                                   lambda b, tables, starts: (b, 0, 0, 0)),
        )
    else:
        if q_blk is None:
            q_blk = s
        if s % q_blk:
            raise ValueError(f"chunk length {s} not divisible by q tile "
                             f"{q_blk}")
        kern = functools.partial(_chunk_grid_kernel, scale=scale, bs=bs,
                                 max_blocks=max_blocks, q_blk=q_blk)

        def qmap(b, qt, blk, tables, starts):
            return (b, qt, 0, 0)

        def kvmap(b, qt, blk, tables, starts):
            return (0, tables[b, blk], 0, 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, s // q_blk, max_blocks),
            in_specs=[
                pl.BlockSpec((None, q_blk, nh, hd), qmap),
                pl.BlockSpec((nh, None, bs, hd), kvmap),
                pl.BlockSpec((nh, None, bs, hd), kvmap),
            ],
            out_specs=pl.BlockSpec((None, q_blk, nh, hd), qmap),
            scratch_shapes=[
                pltpu.VMEM((nh, q_blk), jnp.float32),
                pltpu.VMEM((nh, q_blk), jnp.float32),
                pltpu.VMEM((nh, q_blk, hd), jnp.float32),
            ],
        )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, s, nh, hd), q.dtype),
        interpret=interpret,
    )(block_tables, start_lens, q, k_cache, v_cache)


def paged_chunk_attention_reference(q, k_cache, v_cache, block_tables,
                                    start_lens):
    """Pure-XLA oracle: `PagedChunkView`'s dense linearized-table gather
    with the offset causal mask, bit-for-bit the view's math."""
    B, s, nh, hd = q.shape
    bs = k_cache.shape[2]
    nb = block_tables.shape[1]
    pos = start_lens[:, None] + jnp.arange(s, dtype=start_lens.dtype)
    k_lin = jnp.take(k_cache, block_tables, axis=1).reshape(
        nh, B, nb * bs, hd)
    v_lin = jnp.take(v_cache, block_tables, axis=1).reshape(
        nh, B, nb * bs, hd)
    logits = jnp.einsum("bqhd,hbkd->bhqk", q.astype(jnp.float32),
                        k_lin.astype(jnp.float32)) / math.sqrt(hd)
    kpos = jnp.arange(nb * bs, dtype=pos.dtype)
    mask = kpos[None, :] <= pos[:, :, None]
    logits = jnp.where(mask[:, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,hbkd->bqhd", probs,
                      v_lin.astype(jnp.float32)).astype(q.dtype)


def paged_verify_attention(q, k_cache, v_cache, block_tables, start_lens,
                           interpret=None, strategy=None):
    """Spec-decode verify attention: the k candidate positions of each
    stream attend the cached prefix + themselves through the block
    table.  Mathematically the chunk-prefill contract with s = k
    (candidates sit at start..start+k-1, offset causal), so it reuses
    the chunk kernel — but it is a distinct serving program with its
    own flag and audit row, hence the separate entry point and claim."""
    return paged_chunk_attention(
        q, k_cache, v_cache, block_tables, start_lens,
        interpret=interpret, strategy=strategy,
        _claim_name="paged_spec_verify")


class BlockKVCache:
    """Host-side block allocator + device block pool (the role of the
    reference's block-table manager around `block_multihead_attention`).

    append() writes one decode step's k/v into each sequence's current
    block (allocating a fresh physical block when the previous fills) with
    a single scatter; attend() runs the paged kernel.
    """

    def __init__(self, num_blocks: int, block_size: int, num_heads: int,
                 head_dim: int, batch: int, max_blocks_per_seq: int,
                 dtype=jnp.float32):
        self.bs = block_size
        self.k = jnp.zeros((num_heads, num_blocks, block_size, head_dim),
                           dtype)
        self.v = jnp.zeros_like(self.k)
        self.tables = jnp.zeros((batch, max_blocks_per_seq), jnp.int32)
        self.seq_lens = jnp.zeros((batch,), jnp.int32)
        self._free = list(range(num_blocks - 1, 0, -1))  # block 0 = pad
        self._owned = [[] for _ in range(batch)]
        self._lens = [0] * batch  # host mirror: no device sync per token

    def _alloc(self, b: int) -> int:
        if not self._free:
            raise RuntimeError("BlockKVCache: out of physical blocks")
        slot = len(self._owned[b])
        if slot >= self.tables.shape[1]:
            # out-of-bounds scatter would be silently DROPPED by XLA and
            # attention would lose the overflow tokens — fail loudly
            raise RuntimeError(
                f"BlockKVCache: sequence {b} exceeds max_blocks_per_seq="
                f"{self.tables.shape[1]}")
        blk = self._free.pop()
        self._owned[b].append(blk)
        self.tables = self.tables.at[b, slot].set(blk)
        return blk

    def append(self, k_step, v_step):
        """k_step/v_step: [B, nh, hd] — one token per sequence."""
        B = k_step.shape[0]
        rows, cols = [], []
        for b in range(B):
            pos = self._lens[b]  # host mirror: no device sync per token
            if pos % self.bs == 0:
                self._alloc(b)
            blk = self._owned[b][pos // self.bs]
            rows.append(blk)
            cols.append(pos % self.bs)
            self._lens[b] = pos + 1
        rows = jnp.asarray(rows)
        cols = jnp.asarray(cols)
        # target [nh, B, hd] slots at [:, rows, cols]
        self.k = self.k.at[:, rows, cols].set(
            jnp.moveaxis(k_step, 0, 1))
        self.v = self.v.at[:, rows, cols].set(
            jnp.moveaxis(v_step, 0, 1))
        self.seq_lens = self.seq_lens + 1

    def append_prefill(self, k, v):
        """Bulk-insert a whole prompt: k/v [B, S, nh, hd].  All sequences
        must be at the same (typically zero) length — the prefill case.
        One scatter per block column, not per token."""
        B, S = k.shape[0], k.shape[1]
        if len(set(self._lens)) != 1:
            raise RuntimeError("append_prefill needs equal sequence lengths")
        start = self._lens[0]
        if start % self.bs != 0:
            # fall back to per-token appends for a ragged tail
            for t in range(S):
                self.append(k[:, t], v[:, t])
            return
        nb = (S + self.bs - 1) // self.bs
        pad = nb * self.bs - S
        if pad:
            zeros = jnp.zeros((B, pad) + k.shape[2:], k.dtype)
            k = jnp.concatenate([k, zeros], axis=1)
            v = jnp.concatenate([v, zeros], axis=1)
        # [B, nb, bs, nh, hd] -> per block column [nh, B, bs, hd]
        kb = jnp.moveaxis(k.reshape(B, nb, self.bs, *k.shape[2:]), 3, 0)
        vb = jnp.moveaxis(v.reshape(B, nb, self.bs, *v.shape[2:]), 3, 0)
        for blk in range(nb):
            rows = []
            for b in range(B):
                rows.append(self._alloc(b))
            rows = jnp.asarray(rows)
            self.k = self.k.at[:, rows].set(kb[:, :, blk])
            self.v = self.v.at[:, rows].set(vb[:, :, blk])
        for b in range(B):
            self._lens[b] = start + S
        self.seq_lens = jnp.full_like(self.seq_lens, start + S)

    def attend(self, q, interpret=None):
        return paged_attention(q, self.k, self.v, self.tables,
                               self.seq_lens, interpret=interpret)

    def free(self, b: int):
        """Return sequence b's blocks to the pool."""
        self._free.extend(reversed(self._owned[b]))
        self._owned[b] = []
        self._lens[b] = 0
        self.tables = self.tables.at[b].set(0)
        self.seq_lens = self.seq_lens.at[b].set(0)
